"""Serving engine: pipelined vs blocking dispatcher under load.

The claim under test (parallel/serving.py): the seed dispatcher's fixed
aggregation window + inline host-sync fetch put a floor of
``timeout_ms + device_roundtrip`` under every request; the pipelined
engine's backpressure aggregation (coalesce only while the device is
busy) and completion-thread fetch remove both, so closed-loop
throughput rises and the latency tail collapses. On a 1-core CPU box
the window elimination dominates; on a real accelerator the
dispatch/fetch overlap is the bigger half — PERF_ANALYSIS r8 records
the decomposition.

Two load shapes:
- **closed-loop**: N client threads, each issuing its next request the
  moment the previous answer lands — throughput-bound, the arm ratio is
  the A/B headline.
- **open-loop**: Poisson arrivals at a target rate, submitted without
  waiting — latency-bound; the p50/p95/p99 table is the story (a
  closed loop can't see coordinated omission).

Arms alternate per round (A/B interleaved via benchmarks/ab.py, the
shared harness the autotuner reuses) so machine-load drift hits both
equally.

PR 6 adds two multi-process modes:

- **--cold-start**: subprocess A/B of cold-start-to-``assert_warm()``
  with and without the persisted AOT executable cache
  (parallel/aot_cache.py). Each arm is a FRESH python process (the only
  honest way to measure a cold start); the cached arm must also produce
  bitwise-identical outputs to the uncached arm.
- **--smoke-fleet / --soak-fleet**: open-loop soak against the fleet
  front door (parallel/fleet.py). The parent hosts a warmed FleetRouter
  behind the UI HTTP surface; worker SUBPROCESSES drive Poisson
  arrivals at a target aggregate QPS through ``POST /api/predict`` and
  count ok / shed (HTTP 503) / error. Gates: zero post-warmup
  recompiles (watchdog-asserted), shed rate < 100%, served p99 under a
  CPU-calibrated bound, achieved arrival rate near target.

Usage:
    python benchmarks/serving.py                   # timed A/B + curve
    python benchmarks/serving.py --rate 500        # open-loop point
    python benchmarks/serving.py --smoke           # CI gate: bitwise vs
        # direct model.output, zero recompiles after warmup, pipelined
        # >= 1.3x blocking closed-loop
    python benchmarks/serving.py --precision-ab    # f32/bf16/int8 $/req
    python benchmarks/serving.py --precision-ab --smoke  # CI gate:
        # int8 within top-1 budget of f32, all arms warm, int8 bytes
        # proxy strictly below bf16
    python benchmarks/serving.py --cold-start      # cached vs uncached
    python benchmarks/serving.py --smoke-fleet     # CI fleet gate
    python benchmarks/serving.py --soak-fleet --rate 150 --duration 10
"""

from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import subprocess
import sys
import threading
import time

import numpy as np

from benchmarks import ab
from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import MetricsRegistry
from deeplearning4j_tpu.parallel.serving import ServingEngine

FEATURES = 128


def build_model(seed: int = 7, width: int = 1024):
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=width))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(FEATURES)).build())
    return MultiLayerNetwork(conf).init()


def make_engine(model, *, pipelined: bool, session: str,
                batch_limit: int = 32, timeout_ms: float = 5.0,
                replicas=1, aot_cache_dir=None,
                precision=None) -> ServingEngine:
    # isolated registry per arm: the A/B must not share counters
    return ServingEngine(
        model, batch_limit=batch_limit, timeout_ms=timeout_ms,
        pipelined=pipelined, replicas=replicas,
        feature_shape=(FEATURES,), registry=MetricsRegistry(),
        session_id=session, aot_cache_dir=aot_cache_dir,
        model_version="bench", precision=precision)


def closed_loop(engine: ServingEngine, n_clients: int, n_requests: int,
                req_size: int, seed: int = 0):
    """N clients, each firing its next request on completion. Returns
    (throughput req/s, LatencyRing of client-observed latencies)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(req_size, FEATURES)).astype(np.float32)
    ring = LatencyRing(capacity=n_clients * n_requests)
    barrier = threading.Barrier(n_clients + 1)
    errors = []

    def client():
        barrier.wait()
        try:
            for _ in range(n_requests):
                t0 = time.perf_counter()
                engine.output(x)
                ring.record(time.perf_counter() - t0)
        except Exception as e:      # surface, don't hang the barrier
            errors.append(e)

    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return (n_clients * n_requests) / wall, ring


def open_loop(engine: ServingEngine, rate_hz: float, duration_s: float,
              req_size: int, seed: int = 0):
    """Poisson arrivals at ``rate_hz``, submitted without waiting for
    completions. Returns (achieved req/s, LatencyRing)."""
    rng = np.random.default_rng(seed)
    arrival = random.Random(seed)
    x = rng.normal(size=(req_size, FEATURES)).astype(np.float32)
    ring = LatencyRing(capacity=int(rate_hz * duration_s) + 64)
    pending = []
    t_start = time.perf_counter()
    deadline = t_start + duration_s
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        f = engine.submit(x)
        f.add_done_callback(
            lambda _f, t0=t0: ring.record(time.perf_counter() - t0))
        pending.append(f)
        time.sleep(arrival.expovariate(rate_hz))
    for f in pending:
        f.result()
    wall = time.perf_counter() - t_start
    return len(pending) / wall, ring


def run_timed(args) -> int:
    model = build_model(width=args.width)
    arms = {}
    for name, pipelined in (("blocking", False), ("pipelined", True)):
        arms[name] = make_engine(
            model, pipelined=pipelined, session=name,
            batch_limit=args.batch_limit, timeout_ms=args.timeout_ms,
            replicas=args.replicas)
    try:
        rings = {name: LatencyRing(capacity=1 << 16) for name in arms}

        def _arm(name, eng):
            def go(r):
                t, ring = closed_loop(eng, args.clients, args.requests,
                                      args.req_size, seed=r)
                for v in ring.snapshot():
                    rings[name].record(v)
                return t
            return go

        tput = ab.interleaved({n: _arm(n, e) for n, e in arms.items()},
                              args.rounds)
        med = ab.median_of(tput)
        print(f"closed-loop: {args.clients} clients x {args.requests} "
              f"requests x{args.req_size}, median of {args.rounds} "
              "rounds:")
        for name in arms:
            print(f"  {name:9s} {med[name]:9.1f} req/s   "
                  f"{ab.fmt_quantiles(rings[name])}")
        speedup = med["pipelined"] / med["blocking"]
        print(f"pipelined speedup: {speedup:.2f}x")

        if args.rate:
            t, ring = open_loop(arms["pipelined"], args.rate,
                                args.open_duration, args.req_size)
            print(f"open-loop (Poisson {args.rate:.0f} req/s target): "
                  f"{t:9.1f} req/s achieved   {ab.fmt_quantiles(ring)}")
        for name, eng in arms.items():
            eng.assert_warm()
        if args.assert_speedup and speedup < args.assert_speedup:
            print(f"FAIL: pipelined speedup {speedup:.2f}x below the "
                  f"{args.assert_speedup:.2f}x floor")
            return 1
        return 0
    finally:
        for eng in arms.values():
            eng.shutdown()


def run_smoke(args) -> int:
    """CI gate: (1) serving output bitwise-equal to direct
    ``model.output`` across request sizes (including padded, split and
    co-batched ones); (2) zero recompiles after the warmup sweep,
    watchdog-asserted; (3) pipelined >= 1.3x blocking closed-loop
    throughput. The margin measured on a 1-core CPU box is ~10x
    (PERF_ANALYSIS r8), so the 1.3x floor keeps noise headroom."""
    model = build_model(width=64)
    rng = np.random.default_rng(0)
    eng = make_engine(model, pipelined=True, session="smoke",
                      batch_limit=16)
    try:
        for n in (1, 2, 3, 5, 8, 16, 37):   # 37 > batch_limit: splits
            x = rng.normal(size=(n, FEATURES)).astype(np.float32)
            got = eng.output(x)
            want = np.asarray(model.output(x))
            if got.shape != want.shape or not np.array_equal(got, want):
                print(f"FAIL: serving output diverged from direct "
                      f"model.output at request size {n} "
                      f"(max abs diff "
                      f"{np.max(np.abs(got - want)):.3e})")
                return 1
        # concurrent co-batched requests must slice back bitwise too
        t, _ring = closed_loop(eng, 4, 25, 2)
        got = eng.output(rng.normal(size=(3, FEATURES))
                         .astype(np.float32))
        eng.assert_warm()       # zero recompiles after warmup
        stats = eng.stats()
    finally:
        eng.shutdown()

    # A/B throughput gate on fresh engines (isolated counters)
    arms = {}
    for name, pipelined in (("blocking", False), ("pipelined", True)):
        arms[name] = make_engine(model, pipelined=pipelined,
                                 session=f"smoke-{name}", batch_limit=16)
    try:
        rings = {name: LatencyRing(capacity=1 << 14) for name in arms}

        def _arm(name, e):
            def go(r):
                tp, ring = closed_loop(e, 4, 30, 1, seed=r)
                for v in ring.snapshot():
                    rings[name].record(v)
                return tp
            return go

        tput = ab.interleaved({n: _arm(n, e) for n, e in arms.items()},
                              3)
        med = ab.median_of(tput)
        speedup = med["pipelined"] / med["blocking"]
        for name in arms:
            print(f"  {name:9s} {med[name]:9.1f} req/s   "
                  f"{ab.fmt_quantiles(rings[name])}")
        arms["pipelined"].assert_warm()
    finally:
        for e in arms.values():
            e.shutdown()

    if speedup < 1.3:
        print(f"FAIL: pipelined speedup {speedup:.2f}x below the 1.3x "
              "floor")
        return 1
    print(f"serving smoke: bitwise vs direct output, "
          f"{stats['recompiles_after_warmup']} recompiles after warmup, "
          f"pipelined {speedup:.2f}x blocking")
    return 0


# ---- precision A/B: $/req proxy across f32 / bf16 / int8 -----------------

def run_precision_ab(args, smoke: bool = False) -> int:
    """A/B the serving PrecisionPolicy arms on a $/req cost proxy next
    to the latency columns. Dollar cost on a rented accelerator tracks
    device-seconds and bytes moved, so per completed request we report:

    - **bytes/req** — params-resident bytes x (device batches / requests)
      plus the request's own feature/output payload: the per-request
      share of weight traffic the matmuls pull through the memory
      hierarchy. Int8 holds a quarter of f32's weight bytes (bf16 half),
      so this is the column quantization is buying down.
    - **devms/req** — engine-measured device milliseconds (dispatch to
      ready) per request.
    - **params MB** — resident committed weights (the HBM rent).

    ``--smoke`` gates: int8 answers like f32 (top-1 agreement within
    budget), every arm warm (zero post-warmup recompiles), and int8's
    bytes/req strictly below bf16's — the headline the quantization
    path must actually deliver.
    """
    from deeplearning4j_tpu.parallel.quant import PrecisionPolicy
    width = 64 if smoke else args.width
    batch_limit = 16 if smoke else args.batch_limit
    clients = 4 if smoke else args.clients
    requests = 25 if smoke else args.requests
    rounds = 2 if smoke else args.rounds
    model = build_model(width=width)
    rng = np.random.default_rng(11)
    calib = rng.normal(size=(256, FEATURES)).astype(np.float32)
    eval_x = rng.normal(size=(batch_limit, FEATURES)).astype(np.float32)
    policies = {
        "f32": PrecisionPolicy.f32(),
        "bf16": PrecisionPolicy.bf16(),
        "int8": PrecisionPolicy.int8(calib),
    }
    rows = {}
    outputs = {}
    failures = []
    engines = {}
    base = {}
    rings = {}
    try:
        # every arm alive before timing starts: the interleaved rounds
        # see identical machine load (benchmarks/ab.py methodology)
        for name, policy in policies.items():
            eng = make_engine(model, pipelined=True,
                              session=f"prec-{name}",
                              batch_limit=batch_limit,
                              timeout_ms=args.timeout_ms,
                              precision=policy)
            engines[name] = eng
            outputs[name] = np.asarray(eng.output(eval_x))
            base[name] = (eng.dispatch_count, eng.device_ms_total)
            rings[name] = LatencyRing(capacity=1 << 16)

        def _arm(name, eng):
            def go(r):
                tp, rg = closed_loop(eng, clients, requests,
                                     args.req_size, seed=r)
                for v in rg.snapshot():
                    rings[name].record(v)
                return tp
            return go

        meds = ab.median_of(ab.interleaved(
            {n: _arm(n, e) for n, e in engines.items()}, rounds))

        for name, eng in engines.items():
            d0, ms0 = base[name]
            n_req = clients * requests * rounds
            batches = eng.dispatch_count - d0
            dev_ms = eng.device_ms_total - ms0
            pbytes = eng.params_resident_bytes
            io_bytes = (args.req_size * FEATURES * 4
                        + args.req_size * outputs[name].shape[-1] * 4)
            q = rings[name].quantiles((0.5, 0.99))
            try:
                eng.assert_warm()
            except Exception as e:
                failures.append(f"{name} arm not warm: {e}")
            rows[name] = {
                "tput": meds[name],
                "p50_ms": q[0.5] * 1e3, "p99_ms": q[0.99] * 1e3,
                "params_bytes": pbytes,
                "bytes_per_req": pbytes * (batches / n_req) + io_bytes,
                "devms_per_req": dev_ms / n_req,
            }
    finally:
        for eng in engines.values():
            eng.shutdown()

    print(f"precision A/B: width={width}, {clients} clients x "
          f"{requests} requests x{args.req_size}, median of {rounds} "
          "rounds:")
    print(f"  {'arm':5s} {'req/s':>9s} {'p50':>9s} {'p99':>9s} "
          f"{'paramsMB':>9s} {'bytes/req':>11s} {'devms/req':>10s}")
    for name, r in rows.items():
        print(f"  {name:5s} {r['tput']:9.1f} {r['p50_ms']:8.2f}m "
              f"{r['p99_ms']:8.2f}m {r['params_bytes'] / 1e6:9.3f} "
              f"{r['bytes_per_req']:11.0f} {r['devms_per_req']:10.3f}")

    a_f32 = outputs["f32"].argmax(axis=-1).reshape(-1)
    a_int8 = outputs["int8"].argmax(axis=-1).reshape(-1)
    agreement = float((a_f32 == a_int8).mean())
    print(f"  int8 top-1 agreement vs f32: {agreement:.4f}  "
          f"bytes/req vs bf16: {rows['int8']['bytes_per_req']:.0f} "
          f"vs {rows['bf16']['bytes_per_req']:.0f}")
    if smoke:
        if agreement < 1.0 - args.top1_budget:
            failures.append(
                f"int8 top-1 agreement {agreement:.4f} below the "
                f"{1.0 - args.top1_budget:.4f} floor")
        if not rows["int8"]["bytes_per_req"] < \
                rows["bf16"]["bytes_per_req"]:
            failures.append(
                "int8 bytes/req "
                f"{rows['int8']['bytes_per_req']:.0f} not strictly "
                f"below bf16 {rows['bf16']['bytes_per_req']:.0f}")
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


# ---- cold start: persisted AOT cache A/B (subprocess arms) ---------------

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_child(extra, timeout=600):
    """Run this benchmark in a fresh process, parse the last stdout line
    as JSON (child modes print exactly one JSON line)."""
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.serving"] + extra,
        cwd=_ROOT, capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"child {extra[:2]} failed rc={proc.returncode}:\n"
            f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run_cold_child(args) -> int:
    """One cold-start arm: fresh process builds the model, stands up a
    warmed engine (optionally against a persisted cache), and reports
    the warmup-sweep seconds + an output digest for bitwise comparison.
    Prints exactly one JSON line."""
    import hashlib
    model = build_model(width=args.width)
    t0 = time.perf_counter()
    eng = make_engine(model, pipelined=True, session="cold",
                      batch_limit=16, aot_cache_dir=args.aot_cache_dir)
    build_s = time.perf_counter() - t0
    try:
        eng.assert_warm()
        rng = np.random.default_rng(123)
        x = rng.normal(size=(5, FEATURES)).astype(np.float32)
        out = eng.output(x)
        digest = hashlib.sha256(
            np.ascontiguousarray(out).tobytes()).hexdigest()
        stats = eng.stats()
    finally:
        eng.shutdown()
    print(json.dumps({
        "warmup_s": stats["warmup_s"], "build_s": build_s,
        "out_sha256": digest,
        "aot": stats.get("aot_cache"),
        "recompiles": stats["recompiles_after_warmup"]}))
    return 0


def run_cold_start(args) -> int:
    """Cold-start-to-``assert_warm()``: median over ``--cold-runs``
    fresh processes, uncached vs persisted-cache-warm. The first cached
    process pays the save (reported separately); every later one loads.
    Outputs must be bitwise-identical across every arm."""
    import shutil
    import tempfile
    cache = args.aot_cache_dir or tempfile.mkdtemp(prefix="dl4j-aot-")
    owned = args.aot_cache_dir is None
    base = ["--cold-start-child", "--width", str(args.width)]
    try:
        uncached = [_run_child(base) for _ in range(args.cold_runs)]
        # seed process: state "cold" -> warms live, saves the cache
        seed_run = _run_child(base + ["--aot-cache-dir", cache])
        cached = [_run_child(base + ["--aot-cache-dir", cache])
                  for _ in range(args.cold_runs)]
    finally:
        if owned:
            shutil.rmtree(cache, ignore_errors=True)

    digests = {r["out_sha256"] for r in uncached + [seed_run] + cached}
    med_un = statistics.median(r["warmup_s"] for r in uncached)
    med_ca = statistics.median(r["warmup_s"] for r in cached)
    speedup = med_un / med_ca if med_ca > 0 else float("inf")
    states = [r["aot"]["state"] if r["aot"] else "?" for r in cached]
    print(f"cold start to assert_warm(), width={args.width}, median of "
          f"{args.cold_runs} fresh processes:")
    print(f"  uncached       {med_un * 1e3:8.1f} ms")
    print(f"  cache save     {seed_run['warmup_s'] * 1e3:8.1f} ms "
          "(first process: live warmup + export)")
    print(f"  cache warm     {med_ca * 1e3:8.1f} ms   "
          f"states={states}")
    print(f"  speedup        {speedup:8.2f}x   bitwise-equal outputs: "
          f"{len(digests) == 1}")
    if len(digests) != 1:
        print("FAIL: cached arm output diverged from uncached")
        return 1
    if any(s != "warm" for s in states):
        print("FAIL: a cached arm did not load the persisted table")
        return 1
    if args.assert_cold_speedup and speedup < args.assert_cold_speedup:
        print(f"FAIL: cached cold-start speedup {speedup:.2f}x below "
              f"the {args.assert_cold_speedup:.2f}x floor")
        return 1
    return 0


# ---- fleet soak: multi-process open loop against the front door ----------

def run_soak_worker(args) -> int:
    """One load-generating subprocess: Poisson arrivals at ``--rate``
    against ``--url``/api/predict for ``--duration`` seconds, open-loop
    (arrivals never wait for completions). Prints one JSON line with
    ok/shed/error counts and served latencies."""
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor
    rng = np.random.default_rng(args.seed)
    arrival = random.Random(args.seed)
    x = rng.normal(size=(args.req_size, FEATURES)).astype(np.float32)
    body = json.dumps({"features": x.tolist()}).encode()
    url = args.url.rstrip("/") + "/api/predict"
    counts = {"ok": 0, "shed": 0, "error": 0}
    lat = []
    lock = threading.Lock()

    def one():
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                r.read()
            dt = time.perf_counter() - t0
            with lock:
                counts["ok"] += 1
                lat.append(dt)
        except urllib.error.HTTPError as e:
            e.read()
            with lock:
                counts["shed" if e.code == 503 else "error"] += 1
        except Exception:
            with lock:
                counts["error"] += 1

    attempts = 0
    t_start = time.perf_counter()
    deadline = t_start + args.duration
    with ThreadPoolExecutor(max_workers=64) as pool:
        futs = []
        while time.perf_counter() < deadline:
            futs.append(pool.submit(one))
            attempts += 1
            time.sleep(arrival.expovariate(args.rate))
        for f in futs:
            f.result()
    wall = time.perf_counter() - t_start
    print(json.dumps({
        "attempts": attempts, "wall_s": wall,
        "latencies_ms": [round(v * 1e3, 3) for v in lat], **counts}))
    return 0


def run_fleet(args, smoke: bool) -> int:
    """Parent of the multi-process soak: host a warmed FleetRouter
    behind the UI HTTP surface, fan ``--workers`` load-generating
    subprocesses at it, aggregate, gate."""
    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.serving_module import FleetModule
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    width = 64 if smoke else args.width
    rate = args.rate or (60.0 if smoke else 150.0)
    duration = args.duration
    model = build_model(width=width)
    fleet = FleetRouter(slo_ms=args.slo_ms, window_s=0.5)
    fleet.add_pool("bench", model, pool_size=args.pool_size,
                   batch_limit=16, feature_shape=(FEATURES,),
                   aot_cache_dir=args.aot_cache_dir)
    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(FleetModule(fleet))
    server.start()
    try:
        fleet.assert_warm()         # warm BEFORE traffic
        per_worker = rate / args.workers
        cmd = [sys.executable, "-m", "benchmarks.serving",
               "--soak-worker", "--url", server.url,
               "--rate", str(per_worker),
               "--duration", str(duration),
               "--req-size", str(args.req_size)]
        procs = [subprocess.Popen(cmd + ["--seed", str(i)], cwd=_ROOT,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True)
                 for i in range(args.workers)]
        results = []
        for p in procs:
            out, err = p.communicate(timeout=duration * 10 + 120)
            if p.returncode != 0:
                raise RuntimeError(
                    f"soak worker rc={p.returncode}:\n{err[-2000:]}")
            results.append(json.loads(out.strip().splitlines()[-1]))

        ok = sum(r["ok"] for r in results)
        shed = sum(r["shed"] for r in results)
        errors = sum(r["error"] for r in results)
        attempts = sum(r["attempts"] for r in results)
        lat = sorted(v for r in results for v in r["latencies_ms"])
        wall = max(r["wall_s"] for r in results)
        achieved = attempts / wall

        def q(p):
            return lat[min(len(lat) - 1,
                           int(np.ceil(p * len(lat))) - 1)] if lat else 0

        shed_rate = shed / attempts if attempts else 1.0
        pst = fleet.stats()["pools"]["bench"]
        import urllib.request
        with urllib.request.urlopen(server.url + "/metrics") as r:
            server_metrics = r.read().decode()
        fleet.assert_warm()         # zero recompiles under traffic
        print(f"fleet soak: {args.workers} worker processes, Poisson "
              f"{rate:.0f} req/s aggregate target x {duration:.0f}s, "
              f"slo={args.slo_ms:.0f}ms, pool_size={args.pool_size}:")
        print(f"  attempts={attempts} ({achieved:.1f} req/s achieved)  "
              f"ok={ok}  shed={shed} ({shed_rate * 100:.1f}%)  "
              f"errors={errors}")
        if lat:
            print(f"  served: p50={q(.5):7.2f}ms  p95={q(.95):7.2f}ms  "
                  f"p99={q(.99):7.2f}ms")
        print(f"  router: shed_fraction={pst['shed_fraction']:.3f}  "
              f"windowed_p99={pst['windowed_p99_ms']:.1f}ms  "
              "post-warmup recompiles=0 (watchdog-asserted)")
        failures = []
        if errors:
            failures.append(f"{errors} worker errors (non-shed)")
        if shed_rate >= 1.0:
            failures.append("every request shed")
        if lat and q(.99) > args.fleet_p99_ms:
            failures.append(f"served p99 {q(.99):.1f}ms over the "
                            f"{args.fleet_p99_ms:.0f}ms bound")
        if achieved < 0.5 * rate:
            failures.append(f"achieved arrival rate {achieved:.1f} "
                            f"req/s under half the {rate:.0f} target")
        if "dl4j_fleet_admitted_total" not in server_metrics:
            failures.append("dl4j_fleet_* series missing from /metrics")
        for f in failures:
            print(f"FAIL: {f}")
        return 1 if failures else 0
    finally:
        server.stop()
        fleet.shutdown()


# ---- cluster chaos soak: node kill / rejoin through the remote tier ------

def _start_node(model_zip, node_id, reg_dir, store_dir, log_path,
                slo_ms=1000.0):
    """Spawn one worker node subprocess (the real CLI path: ``serve
    --join``). Output goes to a log file — tail printed on failure."""
    cmd = [sys.executable, "-m", "deeplearning4j_tpu", "serve",
           "--model", model_zip, "--inference-mode", "batched",
           "--batch-limit", "16", "--warmup-shape", str(FEATURES),
           "--ui-port", "0", "--join", reg_dir,
           "--artifact-store", store_dir, "--model-key", "bench",
           "--node-id", node_id, "--slo-ms", str(slo_ms),
           "--drain-timeout", "20"]
    log = open(log_path, "w")
    proc = subprocess.Popen(cmd, cwd=_ROOT, stdout=log,
                            stderr=subprocess.STDOUT)
    return proc, log


def _wait_node(registry, node_id, pid, timeout_s=240.0):
    """Wait for THIS process's registry record (pid-matched, so a
    rejoining node with a crashed predecessor's stale file doesn't
    count until the new process actually published)."""
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        rec = registry.read_all().get(node_id)
        if rec and rec.get("pid") == pid:
            return rec
        time.sleep(0.2)
    raise RuntimeError(f"node {node_id} (pid {pid}) never registered")


def _tail(path, n=2000):
    try:
        with open(path) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def run_cluster(args, smoke: bool) -> int:
    """Chaos soak through the cluster tier (parallel/node.py +
    parallel/remote.py): two worker-node subprocesses join a shared
    registry and warm from one shared artifact store; the parent drives
    Poisson traffic through a RemoteDispatcher while node "a" is
    SIGKILLed mid-soak and a replacement (SAME node id) joins.

    Gates:
    - client-visible errors <= the killed node's in-flight count at the
      kill (everything else retries onto the survivor);
    - served p99 under ``--cluster-p99-ms`` THROUGH the kill+join;
    - node "a"'s circuit breaker opened at least once and is closed
      again at the end (half-open probe recovered onto the rejoiner);
    - the rejoined node warmed from the shared store: AOT state "warm",
      zero recompiles after warmup, and it actually served requests;
    - SIGTERM drain on node "b": exit 0, record deregistered.
    """
    import shutil
    import signal as _signal
    import tempfile
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from deeplearning4j_tpu.models.serialization import save_model
    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
    from deeplearning4j_tpu.parallel.node import NodeRegistry
    from deeplearning4j_tpu.parallel.remote import RemoteDispatcher

    width = 64 if smoke else args.width
    rate = args.rate or (40.0 if smoke else 120.0)
    kill_after = 4.0 if smoke else max(4.0, args.duration * 0.3)
    tail_s = 6.0 if smoke else max(8.0, args.duration * 0.3)

    work = tempfile.mkdtemp(prefix="dl4j-cluster-")
    reg_dir = os.path.join(work, "registry")
    store_dir = os.path.join(work, "store")
    model_zip = os.path.join(work, "model.zip")
    save_model(build_model(width=width), model_zip)
    registry = NodeRegistry(reg_dir, stale_after_s=1.0, dead_after_s=2.5)
    procs = {}
    logs = {}
    handles = []
    failures = []

    def start(node_id):
        p, log = _start_node(model_zip, node_id, reg_dir, store_dir,
                             os.path.join(work, f"{node_id}.log"),
                             slo_ms=args.slo_ms)
        procs.setdefault(node_id, []).append(p)
        handles.append(log)
        logs[node_id] = os.path.join(work, f"{node_id}.log")
        return p

    try:
        # serial start: node "a" pays the warmup sweep and publishes the
        # shared store; "b" (and the rejoiner) must warm from it
        pa = start("a")
        _wait_node(registry, "a", pa.pid)
        if ArtifactStore(store_dir).manifest("bench") is None:
            failures.append("node a did not publish the artifact store")
        pb = start("b")
        rec_b = _wait_node(registry, "b", pb.pid)

        disp = RemoteDispatcher(
            registry, timeout_s=10.0, retries=3, backoff_s=0.05,
            breaker_failures=3, breaker_reset_s=1.0, hedge_after_s=0.5)
        counts = {"ok": 0, "error": 0}
        lat = []
        lock = threading.Lock()
        rng = np.random.default_rng(args.seed)
        x = rng.normal(size=(args.req_size, FEATURES)).astype(np.float32)
        stop = threading.Event()

        def one():
            t0 = time.perf_counter()
            try:
                disp.predict(x)
                dt = time.perf_counter() - t0
                with lock:
                    counts["ok"] += 1
                    lat.append(dt)
            except Exception:   # RemoteError / NoNodesError / transport
                with lock:
                    counts["error"] += 1

        pool = ThreadPoolExecutor(max_workers=64)
        futs = []
        arrival = random.Random(args.seed)

        def drive():
            while not stop.is_set():
                futs.append(pool.submit(one))
                time.sleep(arrival.expovariate(rate))

        driver = threading.Thread(target=drive, daemon=True)
        driver.start()

        # ---- chaos: SIGKILL node a mid-soak --------------------------
        time.sleep(kill_after)
        gossip_a = registry.read_all().get("a", {}).get("stats", {})
        pa.kill()                                      # SIGKILL
        inflight_at_kill = (disp.inflight().get("a", 0)
                            + int(gossip_a.get("pending") or 0)
                            + int(gossip_a.get("inflight") or 0))
        t_kill = time.time()
        # replacement joins under the SAME identity: exercises the
        # stale-record overwrite AND lets the breaker genuinely recover
        pa2 = start("a")
        rec_a2 = _wait_node(registry, "a", pa2.pid)
        rejoin_s = time.time() - t_kill
        time.sleep(tail_s)              # traffic over the full fleet
        stop.set()
        driver.join(timeout=10)
        for f in futs:
            f.result()

        # post-soak probes: make sure the breaker's half-open window
        # has traffic to recover through, and the rejoiner serves
        for _ in range(20):
            try:
                disp.predict(x)
            except Exception:
                pass
            if disp.breaker_state("a") == "closed":
                break
            time.sleep(0.2)

        ok, errors = counts["ok"], counts["error"]
        lat_ms = sorted(v * 1e3 for v in lat)

        def q(p):
            return lat_ms[min(len(lat_ms) - 1,
                              int(np.ceil(p * len(lat_ms))) - 1)] \
                if lat_ms else 0.0

        br = disp._breaker("a")
        with urllib.request.urlopen(
                rec_a2["url"] + "/api/serving/stats", timeout=10) as r:
            stats_a2 = json.loads(r.read())
        served_a2 = int(registry.read_all().get("a", {})
                        .get("stats", {}).get("requests") or 0)
        aot = stats_a2.get("aot_cache") or {}

        print(f"cluster soak: 2 nodes, Poisson {rate:.0f} req/s, "
              f"SIGKILL node a at {kill_after:.0f}s, rejoin in "
              f"{rejoin_s:.1f}s (same id, shared store):")
        print(f"  ok={ok}  errors={errors} "
              f"(bound: in-flight at kill = {inflight_at_kill})")
        print(f"  served: p50={q(.5):7.2f}ms  p95={q(.95):7.2f}ms  "
              f"p99={q(.99):7.2f}ms  (bound {args.cluster_p99_ms:.0f}ms)")
        print(f"  breaker a: opened_total={br.opened_total}  "
              f"state={br.state}")
        print(f"  rejoined a: aot_state={aot.get('state')}  "
              f"recompiles_after_warmup="
              f"{stats_a2.get('recompiles_after_warmup')}  "
              f"served={served_a2}")

        if ok == 0:
            failures.append("no request succeeded")
        if errors > inflight_at_kill:
            failures.append(
                f"{errors} client-visible errors exceed the killed "
                f"node's in-flight window ({inflight_at_kill})")
        if lat_ms and q(.99) > args.cluster_p99_ms:
            failures.append(f"served p99 {q(.99):.1f}ms over the "
                            f"{args.cluster_p99_ms:.0f}ms bound")
        if br.opened_total < 1:
            failures.append("breaker for the killed node never opened")
        if br.state != "closed":
            failures.append(
                f"breaker for node a did not recover (state={br.state})")
        if aot.get("state") != "warm":
            failures.append(
                f"rejoined node not warm from the shared store "
                f"(aot state={aot.get('state')!r}, "
                f"reason={aot.get('reason')!r})")
        if stats_a2.get("recompiles_after_warmup"):
            failures.append(
                f"rejoined node recompiled "
                f"{stats_a2['recompiles_after_warmup']}x after warmup")
        if served_a2 < 1:
            failures.append("rejoined node never served a request")

        # ---- graceful drain: SIGTERM node b --------------------------
        pb.send_signal(_signal.SIGTERM)
        try:
            rc_b = pb.wait(timeout=40)
        except subprocess.TimeoutExpired:
            rc_b = None
        if rc_b != 0:
            failures.append(
                f"SIGTERM drain on node b exited rc={rc_b} "
                f"(want 0):\n{_tail(logs['b'])}")
        if "b" in registry.read_all():
            failures.append(
                "node b's registry record survived its drain")
        else:
            print(f"  drain b: rc=0, deregistered "
                  f"(was {rec_b['url']})")

        pool.shutdown(wait=False)
        disp.shutdown()
        for f in failures:
            print(f"FAIL: {f}")
        if failures:
            for nid, path in logs.items():
                print(f"--- node {nid} log tail ---\n{_tail(path)}")
        return 1 if failures else 0
    finally:
        for plist in procs.values():
            for p in plist:
                if p.poll() is None:
                    p.kill()
        for h in handles:
            h.close()
        shutil.rmtree(work, ignore_errors=True)


# ---- chaos smoke: armed fault plan + deadline propagation ----------------

_CHAOS_PLAN = ("seed={seed};"
               "registry.write:torn_write(count=1,arg=node-a);"
               "store.save:corrupt(count=1,arg=blob);"
               "remote.send:delay(p=0.5,ms=5);"
               "broker.publish:error(count=2)")


def _chaos_pass(work, seed, model):
    """One deterministic sweep over the four fault seams under an armed
    plan; returns (observations, replay signature). Two passes with the
    same seed must agree bitwise on both."""
    from deeplearning4j_tpu.chaos import plan as chaosplan
    from deeplearning4j_tpu.parallel.node import NodeRegistry
    from deeplearning4j_tpu.parallel.remote import RemoteDispatcher
    from deeplearning4j_tpu.streaming.broker import TcpTransport

    plan = chaosplan.arm(
        chaosplan.parse_plan(_CHAOS_PLAN.format(seed=seed)))
    obs = {}
    try:
        # registry: torn heartbeat record -> classified dead, next
        # clean beat heals it
        nreg = NodeRegistry(os.path.join(work, "reg"))
        nreg.write("node-a", "http://a")            # torn (count=1)
        rec = nreg.snapshot()["node-a"]
        nreg.write("node-a", "http://a")            # clean overwrite
        obs["registry"] = (rec["health"], bool(rec.get("corrupt")),
                           nreg.snapshot()["node-a"]["health"])

        # store: first process saves the AOT cache with one blob
        # corrupted in flight; a joining process must quarantine it,
        # live-compile that bucket, and still answer bitwise-correctly
        cache = os.path.join(work, "aot")
        e1 = make_engine(model, pipelined=True, session="chaos-save",
                         batch_limit=4, aot_cache_dir=cache)
        try:
            e1.assert_warm()
        finally:
            e1.shutdown()
        e2 = make_engine(model, pipelined=True, session="chaos-join",
                         batch_limit=4, aot_cache_dir=cache)
        try:
            e2.assert_warm()
            rng = np.random.default_rng(0)
            x = rng.normal(size=(4, FEATURES)).astype(np.float32)
            bitwise = np.array_equal(np.asarray(e2.output(x)),
                                     np.asarray(model.output(x)))
            st = e2.stats()["aot_cache"]
            obs["store"] = (st["quarantined"], st["state"], bitwise)
        finally:
            e2.shutdown()

        # remote: a chaos-delayed node is absorbed by the dispatcher —
        # every client call still succeeds (zero-error budget)
        nreg.write("n1", "http://n1")
        nreg.write("n2", "http://n2")
        calls = []
        ok_body = json.dumps({"output": [[0.0]], "n": 1}).encode()

        def transport(url, body, timeout_s):
            calls.append(url)
            return 200, {}, ok_body

        disp = RemoteDispatcher(nreg, transport=transport,
                                metrics=MetricsRegistry(),
                                snapshot_ttl_s=0.0,
                                sleep=lambda s: None, seed=0, retries=2)
        try:
            served = sum(disp.predict([[1.0]])["n"] for _ in range(20))
        finally:
            disp.shutdown()
        obs["remote"] = (served, len(calls))

        # broker: injected connection drops ride the reconnect path;
        # then a REAL broker restart on the same port is survived too
        t = TcpTransport(backoff_base_s=0.01, registry=MetricsRegistry())
        t.serve()
        try:
            t.publish("chaos", b"m1")       # 2 injected drops, lands
            got1 = t.poll("chaos", timeout=2.0)
            rec_injected = t.reconnects
            port = t.port
            t._server.shutdown()            # kill the broker...
            t._server.server_close()
            t._server = None
            restarted = TcpTransport(port=port)
            restarted.serve()               # ...and restart, same port
            try:
                t.poll("chaos", timeout=0.05)  # flush the stale conn
                t.publish("chaos", b"m2")
                got2 = t.poll("chaos", timeout=2.0)
            finally:
                restarted.close()
            obs["broker"] = (got1, rec_injected, got2)
        finally:
            t.close()

        return obs, plan.replay_signature()
    finally:
        chaosplan.disarm()


def run_chaos(args, smoke: bool = True) -> int:
    """CI chaos gate: deterministic fault sweep (armed plan over the
    registry / artifact-store / remote-dispatch / broker seams, replayed
    bitwise), deadline propagation through the HTTP front door (expired
    -> 504, never dispatched), and an empty graftlint baseline."""
    import shutil
    import tempfile
    import urllib.error
    import urllib.request
    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.serving_module import FleetModule
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    width = 32 if smoke else args.width
    seed_a, seed_b = 42 + args.seed, 43 + args.seed
    model = build_model(width=width)
    work = tempfile.mkdtemp(prefix="dl4j-chaos-")
    failures = []
    try:
        print(f"chaos smoke: plan '{_CHAOS_PLAN.format(seed=seed_a)}'")
        obs1, sig1 = _chaos_pass(os.path.join(work, "p1"), seed_a, model)
        obs2, sig2 = _chaos_pass(os.path.join(work, "p2"), seed_a, model)
        obs3, sig3 = _chaos_pass(os.path.join(work, "p3"), seed_b, model)

        torn, corrupt, healed = obs1["registry"]
        quarantined, state, bitwise = obs1["store"]
        served, calls = obs1["remote"]
        got1, rec_injected, got2 = obs1["broker"]
        fired = {(s, k) for s, k, _, _ in sig1}
        print(f"  registry: torn record -> {torn} (corrupt={corrupt}), "
              f"next beat -> {healed}")
        print(f"  store:    quarantined={quarantined} "
              f"state={state} bitwise={bitwise}")
        print(f"  remote:   {served}/20 served across {calls} sends "
              "(delays absorbed, zero client errors)")
        print(f"  broker:   injected drops -> {rec_injected} reconnects"
              f", delivered={got1 == b'm1'}; restart survived="
              f"{got2 == b'm2'}")
        print(f"  replay:   {len(sig1)} injections; same-seed pass "
              f"identical={(obs1, sig1) == (obs2, sig2)}; "
              f"seed+1 differs={sig3 != sig1}")
        if (torn, corrupt, healed) != ("dead", True, "alive"):
            failures.append(
                f"torn registry record not dead->alive: {obs1['registry']}")
        if quarantined != 1 or state != "warm" or not bitwise:
            failures.append(
                "joining engine did not quarantine the corrupt blob and "
                f"live-compile warm: {obs1['store']}")
        if served != 20:
            failures.append(
                f"remote tier lost requests under injected delay: "
                f"{served}/20")
        if got1 != b"m1" or rec_injected != 2 or got2 != b"m2":
            failures.append(
                f"broker drops/restart not absorbed: {obs1['broker']}")
        if (obs1, sig1) != (obs2, sig2):
            failures.append("same-seed chaos pass not bitwise identical")
        if sig3 == sig1:
            failures.append("different seed replayed the same signature")
        missing = {("registry.write", "torn_write"),
                   ("store.save", "corrupt"), ("remote.send", "delay"),
                   ("broker.publish", "error")} - fired
        if missing:
            failures.append(f"plan clauses never fired: {sorted(missing)}")

        # deadline propagation through the real front door (disarmed)
        reg = MetricsRegistry()
        fleet = FleetRouter(slo_ms=args.slo_ms, window_s=0.5,
                            registry=reg)
        fleet.add_pool("bench", model, pool_size=1, batch_limit=4,
                       feature_shape=(FEATURES,))
        server = UIServer(port=0)
        server.attach(InMemoryStatsStorage())
        server.register_module(FleetModule(fleet))
        server.start()
        try:
            fleet.assert_warm()
            url = server.url + "/api/predict"
            rng = np.random.default_rng(1)
            body = json.dumps({"features": rng.normal(
                size=(1, FEATURES)).tolist()}).encode()

            def post(deadline_ms):
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json",
                             "X-Deadline-Ms": deadline_ms})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            def admitted():
                m = reg.get_metric("dl4j_fleet_admitted_total")
                return sum(m.series().values()) if m is not None else 0.0

            before = admitted()
            code, payload = post("0.000001")       # expired at ingress
            expired_ok = (code == 504
                          and json.loads(payload).get("error")
                          == "deadline" and admitted() == before)
            code2, _ = post("30000")               # generous budget
            print(f"  deadline: expired -> HTTP {code} "
                  f"(dispatched={admitted() != before and code != 504}),"
                  f" fresh budget -> HTTP {code2}")
            if not expired_ok:
                failures.append(
                    f"expired deadline not shed pre-dispatch: HTTP "
                    f"{code}, admitted {before}->{admitted()}")
            if code2 != 200:
                failures.append(
                    f"request with fresh budget failed: HTTP {code2}")
            shed = reg.get_metric("dl4j_fleet_shed_total")
            if shed is None or shed.get(model="bench",
                                        reason="deadline") != 1.0:
                failures.append(
                    "dl4j_fleet_shed_total{reason=deadline} != 1")
        finally:
            server.stop()
            fleet.shutdown()

        # hot paths must stay chaos-clean (zero-overhead contract)
        lint = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--baseline",
             os.path.join("tools", "graftlint", "baseline.json")],
            cwd=_ROOT, capture_output=True, text=True, timeout=900)
        print("  graftlint: baseline "
              + ("empty" if lint.returncode == 0 else "VIOLATED"))
        if lint.returncode != 0:
            failures.append("graftlint baseline not empty:\n"
                            + lint.stdout[-2000:] + lint.stderr[-2000:])

        for f in failures:
            print(f"FAIL: {f}")
        return 1 if failures else 0
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8,
                    help="closed-loop client threads")
    ap.add_argument("--requests", type=int, default=100,
                    help="requests per client per round")
    ap.add_argument("--req-size", type=int, default=1,
                    help="examples per request")
    ap.add_argument("--rounds", type=int, default=5,
                    help="interleaved A/B rounds")
    ap.add_argument("--batch-limit", type=int, default=32)
    ap.add_argument("--timeout-ms", type=float, default=5.0,
                    help="aggregation upper bound (the blocking arm's "
                    "fixed window)")
    ap.add_argument("--replicas", default=1,
                    help="device replicas (int or 'auto')")
    ap.add_argument("--width", type=int, default=1024,
                    help="hidden width of the benchmark model")
    ap.add_argument("--rate", type=float, default=None,
                    help="add an open-loop (Poisson) point at this "
                    "req/s target")
    ap.add_argument("--open-duration", type=float, default=5.0,
                    help="open-loop measurement window, seconds")
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="exit 1 when pipelined/blocking falls below")
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: bitwise outputs, zero post-warmup "
                    "recompiles, >=1.3x closed-loop")
    # precision A/B ($/req proxy across serving precisions)
    ap.add_argument("--precision-ab", action="store_true",
                    help="A/B f32 / bf16 / int8 serving arms on a "
                    "$/req proxy (bytes moved, device ms, resident "
                    "params) next to p50/p99; with --smoke also gates "
                    "int8 accuracy + bytes strictly below bf16")
    ap.add_argument("--top1-budget", type=float, default=0.02,
                    help="--precision-ab --smoke: max tolerated int8 "
                    "top-1 disagreement vs f32")
    # cold start (persisted AOT cache A/B)
    ap.add_argument("--cold-start", action="store_true",
                    help="subprocess A/B: cold-start-to-assert_warm "
                    "with vs without the persisted AOT cache")
    ap.add_argument("--cold-runs", type=int, default=3,
                    help="fresh processes per cold-start arm (median)")
    ap.add_argument("--assert-cold-speedup", type=float, default=None,
                    help="exit 1 when cached/uncached cold-start falls "
                    "below this ratio")
    ap.add_argument("--aot-cache-dir", default=None,
                    help="persisted AOT cache location (default: a "
                    "temp dir, removed afterwards)")
    # fleet soak (multi-process open loop)
    ap.add_argument("--smoke-fleet", action="store_true",
                    help="CI gate: short multi-process Poisson soak "
                    "through the fleet front door")
    ap.add_argument("--soak-fleet", action="store_true",
                    help="longer fleet soak at --rate/--duration")
    ap.add_argument("--workers", type=int, default=2,
                    help="load-generating worker subprocesses")
    ap.add_argument("--duration", type=float, default=3.0,
                    help="soak measurement window, seconds")
    ap.add_argument("--slo-ms", type=float, default=1000.0,
                    help="router p99 SLO for the soak")
    ap.add_argument("--fleet-p99-ms", type=float, default=750.0,
                    help="served-p99 gate for the soak (CPU-calibrated)")
    ap.add_argument("--pool-size", type=int, default=1,
                    help="engines in the soak's replica pool")
    # cluster chaos soak (worker-node subprocesses + kill/rejoin)
    ap.add_argument("--smoke-cluster", action="store_true",
                    help="CI gate: 2 worker nodes join a gossiped "
                    "registry + shared artifact store; SIGKILL one "
                    "mid-soak, rejoin same-id, SIGTERM-drain the other")
    ap.add_argument("--soak-cluster", action="store_true",
                    help="longer cluster chaos soak at --rate/--duration")
    ap.add_argument("--cluster-p99-ms", type=float, default=2000.0,
                    help="served-p99 gate through the kill+join "
                    "(CPU-calibrated; retries ride the backoff curve)")
    # fault-injection smoke (deterministic armed chaos plan)
    ap.add_argument("--smoke-chaos", action="store_true",
                    help="CI gate: deterministic fault sweep under an "
                    "armed DL4J_CHAOS plan (torn registry record, "
                    "corrupted AOT blob, delayed remote sends, broker "
                    "drops + restart), bitwise same-seed replay, "
                    "expired-deadline -> 504 without device dispatch, "
                    "empty graftlint baseline")
    ap.add_argument("--seed", type=int, default=0)
    # internal child modes (spawned by --cold-start / --*-fleet)
    ap.add_argument("--cold-start-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--soak-worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--url", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.replicas != "auto":
        args.replicas = int(args.replicas)
    if args.soak_worker:
        return run_soak_worker(args)
    if args.cold_start_child:
        return run_cold_child(args)
    if args.cold_start:
        return run_cold_start(args)
    if args.precision_ab:
        return run_precision_ab(args, smoke=args.smoke)
    if args.smoke_fleet or args.soak_fleet:
        return run_fleet(args, smoke=args.smoke_fleet)
    if args.smoke_cluster or args.soak_cluster:
        return run_cluster(args, smoke=args.smoke_cluster)
    if args.smoke_chaos:
        return run_chaos(args, smoke=True)
    return run_smoke(args) if args.smoke else run_timed(args)


if __name__ == "__main__":
    import sys
    sys.exit(main())
