"""Fed vs unfed input pipeline: does the DeviceFeeder hide host ETL?

The claim under test (datasets/feeder.py): with prefetch on, host-side
batch production (decode/augment — simulated here as a sleep) and the
host→device staging issue overlap the asynchronously-dispatched step,
so epoch wall time approaches max(etl, compute) per batch instead of
their sum. The unfed arm (``fit(..., prefetch=0)``) serializes the two
— the pre-feeder behavior.

Arms run as alternating whole epochs (A/B interleaved, like
telemetry_overhead.py) so machine-load drift hits both equally. The fed
arm carries a SpanTracer; the report includes its cumulative
``feed_stall`` time — the portion of ETL the pipeline FAILED to hide
(0 = fully overlapped) — which is the evidence row PERF_ANALYSIS r7
quotes.

Usage:
    python benchmarks/input_pipeline.py                 # timed A/B
    python benchmarks/input_pipeline.py --k-steps 4     # + fused arm
    python benchmarks/input_pipeline.py --smoke         # correctness
        # only (bitwise fed-vs-unfed check + span evidence), no timing
        # gate — the runtests.sh CPU tier
    python benchmarks/input_pipeline.py --assert-speedup 1.5
"""

from __future__ import annotations

import argparse
import statistics
import time

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator


def build_model(seed: int = 7, width: int = 1024):
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.feedforward import DenseLayer
    from deeplearning4j_tpu.nn.layers.output import OutputLayer
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.ops.losses import LossFunction
    from deeplearning4j_tpu.optimize.updaters import Adam
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=width))
            .layer(OutputLayer(n_out=10, loss=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(128)).build())
    return MultiLayerNetwork(conf).init()


def make_batches(n: int, batch: int = 512, seed: int = 0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, 128)).astype(np.float32)
        idx = rng.integers(0, 10, batch)
        y = np.zeros((batch, 10), np.float32)
        y[np.arange(batch), idx] = 1.0
        out.append(DataSet(x, y))
    return out


class SleepyIterator(DataSetIterator):
    """In-memory batches behind a per-batch host-ETL delay — the
    decode/augment cost a real reader pays. time.sleep releases the
    GIL, so the async prefetch thread genuinely overlaps it."""

    def __init__(self, batches, etl_s: float):
        self._batches = batches
        self._etl_s = etl_s

    def __iter__(self):
        for b in self._batches:
            if self._etl_s > 0:
                time.sleep(self._etl_s)
            yield b

    @property
    def batch_size(self):
        return self._batches[0].num_examples()


def _epoch_time(model, batches, etl_s, **fit_kw) -> float:
    it = SleepyIterator(batches, etl_s)
    t0 = time.perf_counter()
    model.fit(it, epochs=1, **fit_kw)
    return time.perf_counter() - t0


def _stall_ms(tracer) -> float:
    return sum(e["dur"] for e in tracer._events
               if e["name"] == "feed_stall") / 1e3


def run_timed(args) -> int:
    from deeplearning4j_tpu.observe import SpanTracer

    batches = make_batches(args.batches, batch=args.batch)
    unfed = build_model(width=args.width)
    fed = build_model(width=args.width)
    fed_tracer = SpanTracer()
    fed.set_tracer(fed_tracer)
    arms = [("unfed", unfed, dict(prefetch=0)),
            ("fed", fed, dict())]
    if args.k_steps > 1:
        fused = build_model(width=args.width)
        arms.append(("fed+scan", fused, dict(k_steps=args.k_steps)))

    # warmup epoch per arm: compile outside the timed region
    for _, model, kw in arms:
        _epoch_time(model, batches[:max(2, args.k_steps)], 0.0, **kw)

    times = {name: [] for name, _, _ in arms}
    for _ in range(args.rounds):
        for name, model, kw in arms:
            times[name].append(
                _epoch_time(model, batches, args.etl_ms / 1e3, **kw))

    med = {name: statistics.median(ts) for name, ts in times.items()}
    n = len(batches)
    print(f"{n} batches/epoch, {args.etl_ms:.1f} ms simulated host ETL "
          f"per batch, median of {args.rounds} epochs per arm:")
    for name in times:
        print(f"  {name:9s} {med[name] * 1e3 / n:8.3f} ms/step "
              f"({n / med[name]:7.1f} steps/s)")
    speedup = med["unfed"] / med["fed"]
    stall = _stall_ms(fed_tracer)
    total_etl = args.etl_ms * n * args.rounds
    print(f"fed speedup:   {speedup:.2f}x")
    print(f"feed_stall:    {stall:.1f} ms unhidden of "
          f"{total_etl:.0f} ms ETL issued to the fed arm "
          f"({100 * stall / max(total_etl, 1e-9):.1f}% leaked)")
    if args.k_steps > 1:
        print(f"fed+scan:      {med['unfed'] / med['fed+scan']:.2f}x "
              f"vs unfed (k={args.k_steps})")

    if args.assert_speedup and speedup < args.assert_speedup:
        print(f"FAIL: fed speedup {speedup:.2f}x below the "
              f"{args.assert_speedup:.2f}x floor")
        return 1
    return 0


def run_smoke(args) -> int:
    """Correctness-only tier: the fed path must replay the unfed
    trajectory bitwise and leave span evidence of staged transfers.
    No timing gate — CI boxes are too noisy for a ratio assert."""
    import jax
    from deeplearning4j_tpu.observe import SpanTracer

    batches = make_batches(8, batch=64)
    unfed = build_model(width=64)
    fed = build_model(width=64)
    tracer = SpanTracer()
    fed.set_tracer(tracer)
    unfed.fit(SleepyIterator(batches, 0.0), epochs=1, prefetch=0)
    fed.fit(SleepyIterator(batches, 0.0), epochs=1)
    a = jax.tree_util.tree_leaves(jax.device_get(unfed.train_state.params))
    b = jax.tree_util.tree_leaves(jax.device_get(fed.train_state.params))
    for x, y in zip(a, b):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            print("FAIL: fed trajectory diverged from unfed")
            return 1
    names = {e["name"] for e in tracer._events}
    for required in ("etl", "host_to_device"):
        if required not in names:
            print(f"FAIL: no '{required}' span from the fed run")
            return 1
    print("input_pipeline smoke: fed == unfed bitwise, "
          f"{sum(1 for e in tracer._events if e['name'] == 'host_to_device')}"
          " staged transfers traced")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batches", type=int, default=60,
                    help="batches per epoch")
    ap.add_argument("--rounds", type=int, default=5,
                    help="timed epochs per arm (interleaved)")
    ap.add_argument("--etl-ms", type=float, default=8.0,
                    help="simulated host ETL per batch (the default "
                         "roughly matches the default model's CPU step "
                         "time — the regime the double buffer targets)")
    ap.add_argument("--width", type=int, default=1024,
                    help="hidden width of the benchmark model")
    ap.add_argument("--batch", type=int, default=512,
                    help="examples per batch")
    ap.add_argument("--k-steps", type=int, default=1,
                    help=">1 adds a fused-dispatch arm")
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="exit 1 when fed/unfed speedup falls below")
    ap.add_argument("--smoke", action="store_true",
                    help="correctness-only CI tier (no timing gate)")
    args = ap.parse_args(argv)
    return run_smoke(args) if args.smoke else run_timed(args)


if __name__ == "__main__":
    import sys
    sys.exit(main())
