"""Flash-attention BACKWARD bench: Pallas dq/dk/dv kernels vs (a) the
jnp/scan blockwise reference VJP and (b) plain XLA attention's autodiff,
at long sequence lengths (VERDICT r3 #2 acceptance: measured bwd
ms/layer beats the XLA VJP at T=4096/16384).

Run on the TPU chip:  python benchmarks/flash_bwd_bench.py
"""

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def bench_grad(grad_fn, q, k, v, iters=8):
    """K iterations inside ONE jitted dispatch (the repo's standard
    tunnel-amortization), chained through a scalar so no iteration can be
    CSE'd or deduped."""
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def many(q, k, v):
        def body(i, qc):
            gq, gk, gv = grad_fn(qc, k, v)
            # chain ALL THREE grads into the carry — consuming only gq
            # lets XLA dead-code-eliminate the whole dK/dV kernel
            # (verified: optimized HLO shrinks ~32%)
            return qc + (gq + gk + gv).astype(qc.dtype) * 1e-6
        return jnp.sum(lax.fori_loop(0, iters, body, q)
                       .astype(jnp.float32))

    float(many(q, k, v))                        # compile + warm
    best = float("inf")
    for rep in range(1, 4):
        # distinct inputs (tunnel caches identical dispatches), SAME
        # dtype (an f32 promotion would silently retrace), and sync by
        # VALUE fetch — block_until_ready alone returns early on the
        # tunnel backend
        q2 = (q.astype(jnp.float32) + rep * 1e-3).astype(q.dtype)
        jax.block_until_ready(q2)
        t0 = time.perf_counter()
        float(many(q2, k, v))
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def bench_fwd(fn, q, k, v, iters=8):
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def many(q, k, v):
        def body(i, qc):
            o = fn(qc, k, v)
            return qc + o.astype(qc.dtype) * 1e-6    # real data dep
        return jnp.sum(lax.fori_loop(0, iters, body, q)
                       .astype(jnp.float32))

    float(many(q, k, v))
    best = float("inf")
    for rep in range(1, 4):
        q2 = (q.astype(jnp.float32) + rep * 1e-3).astype(q.dtype)
        jax.block_until_ready(q2)
        t0 = time.perf_counter()
        float(many(q2, k, v))
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1000.0


def run(t, h=16, dh=64, n=1, causal=True, dtype=jnp.bfloat16,
        iters=None):
    iters = iters if iters is not None else (32 if t <= 8192 else 8)
    from deeplearning4j_tpu.nn.layers.attention import (
        scaled_dot_product_attention)
    from deeplearning4j_tpu.ops.pallas_kernels import flash_attention

    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(rng.normal(0, 1, (n, t, h, dh)), dtype)
    q, k, v = mk(), mk(), mk()

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(scaled_dot_product_attention(q, k, v,
                                                    causal=causal)
                       .astype(jnp.float32) ** 2)

    g_xla = jax.grad(loss_xla, argnums=(0, 1, 2))
    fwd_flash = functools.partial(flash_attention, causal=causal)

    res = {"t": t, "fwd_flash_ms": bench_fwd(fwd_flash, q, k, v, iters=iters)}

    os.environ["DL4J_FLASH_BWD"] = "pallas"
    jax.clear_caches()
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))
    res["fwdbwd_pallas_ms"] = bench_grad(g_flash, q, k, v, iters=iters)

    os.environ["DL4J_FLASH_BWD"] = "xla"
    jax.clear_caches()
    g_flash2 = jax.grad(loss_flash, argnums=(0, 1, 2))
    res["fwdbwd_scanref_ms"] = bench_grad(g_flash2, q, k, v, iters=iters)
    gb = jax.jit(g_flash2)(q, k, v)     # traced while env=xla
    gb = [jnp.asarray(np.asarray(a)) for a in gb]
    os.environ["DL4J_FLASH_BWD"] = "pallas"
    jax.clear_caches()

    try:
        res["fwdbwd_xla_ms"] = bench_grad(g_xla, q, k, v, iters=iters)
    except Exception as e:          # 16k*16k scores may OOM in XLA
        res["fwdbwd_xla_ms"] = f"OOM ({type(e).__name__})"
    # numeric agreement spot check (bf16 tolerance)
    ga = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    err = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                    - b.astype(jnp.float32))))
              for a, b in zip(ga, gb))
    res["pallas_vs_scanref_max_abs_err"] = err
    return res


if __name__ == "__main__":
    for t in (4096, 16384):
        print(run(t))
