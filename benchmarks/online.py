"""Online learning soak: train-and-serve in one process (ISSUE 10).

The claim under test (deeplearning4j_tpu/online/): a model can serve a
Poisson request stream WHILE an OnlineLearner incrementally fits it
from a broker sample stream, and the promotion gate hot-swaps improved
params into the warm AOT executables with **zero recompiles** — the
swap is invisible to the latency tail. The RegressionSentinel guards
the other direction: a degraded candidate never reaches serving
through the gate, and if one is forced through, the live holdout probe
rolls it back to a bitwise-identical standby.

Scenario (the demo model is the committed SimpleCNN digits artifact,
zoo/weights/simplecnn_digits.zip — a real conv+batchnorm stack, not a
toy dense net):

1. Restore the artifact, then DEGRADE its output layer (zeroed) — the
   process starts serving a deliberately-bad head so the gate has
   headroom to demonstrate a promotion.
2. A publisher thread feeds Poisson-timed RAGGED digit micro-batches
   to an in-process broker topic; the OnlineLearner fits off it
   (holdout batches diverted, never trained on).
3. A client thread drives Poisson predict traffic the whole time,
   recording client-observed latency through every swap.
4. The promotion gate runs until the retrained head is promoted.
5. A freshly re-degraded candidate is offered: the gate must REJECT it.
6. The same candidate is FORCED through: the sentinel's live score
   probe must roll it back, restoring bitwise-identical params.

Smoke gates (CI, CPU):
- promotion happens within ``--promote-window`` seconds;
- the degraded candidate is rejected (reason "worse");
- the forced degraded promotion is rolled back (reason "score") and
  the restored committed params are BITWISE equal to the pre-force
  snapshot;
- ``FleetRouter.assert_warm()`` — zero post-warmup recompiles across
  promote + forced promote + rollback (watchdog-asserted);
- client-observed p99 under ``--p99-bound`` seconds through it all;
- every serve request answered (no errors; no SLO → no shedding).

Usage:
    python -m benchmarks.online --smoke      # CI gate (above)
    python -m benchmarks.online --duration 60 --rate 20  # longer soak
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

import numpy as np

from deeplearning4j_tpu.observe.latency import LatencyRing
from deeplearning4j_tpu.observe.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# demo model: the committed SimpleCNN digits artifact, degradable head
# ---------------------------------------------------------------------------

def load_demo_model():
    from deeplearning4j_tpu.zoo.models import SimpleCNN
    return SimpleCNN().init_pretrained(flavor="digits")


def degrade_head(model):
    """Zero the output layer in place: a uniform-softmax head (loss
    ~ln(10)) over an intact conv trunk — bad enough to gate on, easy
    enough to retrain quickly."""
    import jax.numpy as jnp
    name = model.layers[-1].name
    ts = model.train_state
    params = dict(ts.params)
    params[name] = {k: jnp.zeros_like(v)
                    for k, v in params[name].items()}
    model.train_state = ts._replace(params=params)
    return model


def degrade_candidate(cand):
    """A Candidate with the same zeroed head (host-side numpy)."""
    params = {k: dict(v) if isinstance(v, dict) else v
              for k, v in cand.params.items()}
    last = sorted(params, key=lambda s: int(s.rsplit("_", 1)[-1]))[-1]
    params[last] = {k: np.zeros_like(np.asarray(v))
                    for k, v in params[last].items()}
    return cand._replace(params=params)


def digits_batches(seed=0):
    """Endless ragged micro-batches of real NHWC digits."""
    from deeplearning4j_tpu.datasets.fetchers import DigitsDataSetIterator
    x, y = DigitsDataSetIterator.fetch(train=True)
    x = x.reshape(-1, 28, 28, 1)
    oh = np.eye(10, dtype=np.float32)[y]
    rng = np.random.default_rng(seed)
    while True:
        n = int(rng.integers(4, 17))       # ragged: 4..16 examples
        idx = rng.integers(0, x.shape[0], size=n)
        yield x[idx], oh[idx]


# ---------------------------------------------------------------------------
# load threads
# ---------------------------------------------------------------------------

class PoissonPublisher(threading.Thread):
    def __init__(self, transport, topic, rate_hz, seed=1):
        super().__init__(daemon=True, name="online-bench-pub")
        self.transport, self.topic = transport, topic
        self.rate_hz = rate_hz
        self.batches = digits_batches(seed)
        self.rng = np.random.default_rng(seed + 1)
        self.published = 0
        self.stop_event = threading.Event()

    def run(self):
        from deeplearning4j_tpu.online import publish_samples
        while not self.stop_event.is_set():
            fx, fy = next(self.batches)
            publish_samples(self.transport, self.topic, fx, fy)
            self.published += 1
            self.stop_event.wait(self.rng.exponential(1.0 / self.rate_hz))


class PoissonClient(threading.Thread):
    """Open-loop-ish predict traffic: Poisson think time between
    requests, client-observed latency into a ring."""

    def __init__(self, online, rate_hz, seed=2):
        super().__init__(daemon=True, name="online-bench-client")
        self.online = online
        self.rate_hz = rate_hz
        self.rng = np.random.default_rng(seed)
        self.ring = LatencyRing(capacity=65536)
        self.ok = 0
        self.errors = 0
        self.stop_event = threading.Event()
        from deeplearning4j_tpu.datasets.fetchers import (
            DigitsDataSetIterator)
        x, _ = DigitsDataSetIterator.fetch(train=False)
        self.x = x.reshape(-1, 28, 28, 1)

    def run(self):
        while not self.stop_event.is_set():
            n = int(self.rng.integers(1, 5))
            idx = self.rng.integers(0, self.x.shape[0], size=n)
            t0 = time.perf_counter()
            try:
                self.online.output(self.x[idx])
                self.ok += 1
            except Exception:
                self.errors += 1
            self.ring.record(time.perf_counter() - t0)
            self.stop_event.wait(self.rng.exponential(1.0 / self.rate_hz))


# ---------------------------------------------------------------------------
# the soak
# ---------------------------------------------------------------------------

def trees_equal(a, b) -> bool:
    import jax
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    return ta == tb and len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def run(args) -> int:
    from deeplearning4j_tpu.online import OnlineServing
    from deeplearning4j_tpu.streaming.broker import InProcessTransport

    print("restoring SimpleCNN digits artifact (demo model), "
          "degrading its head for promotion headroom")
    model = degrade_head(load_demo_model())
    transport = InProcessTransport()
    online = OnlineServing(
        model, transport, topic="train", model_name="digits",
        feature_shape=(28, 28, 1), batch_limit=8,
        holdout_every=4, holdout_max=args.holdout_max,
        holdout_batch=64, min_delta=0.0,
        sentinel_window_s=args.promote_window,
        registry=MetricsRegistry())
    # the bench drives gate and sentinel itself (deterministic CI);
    # the learner still trains on its own thread
    online.start(background_promotion=False)
    publisher = PoissonPublisher(transport, "train", args.publish_rate)
    client = PoissonClient(online, args.rate)
    publisher.start()
    client.start()
    promoter, sentinel = online.promoter, online.sentinel
    failures = []
    try:
        if args.duration:
            # soak phase: serve-while-train, no gate pressure yet
            print(f"soaking {args.duration:.0f}s before the gates")
            time.sleep(args.duration)
        # ---- gate 1: promotion within the window ------------------------
        deadline = time.time() + args.promote_window
        decision = None
        while time.time() < deadline:
            d = promoter.run_once()
            if d.reason != "no_candidate":
                print(f"  gate: promoted={d.promoted} reason={d.reason} "
                      f"cand={d.candidate_score} active={d.active_score} "
                      f"it={d.iteration}")
            if d.promoted:
                decision = d
                break
            time.sleep(1.0)
        if decision is None:
            failures.append(
                f"no promotion within {args.promote_window:.0f}s "
                f"(learner at {online.learner.iterations} iterations)")
        else:
            print(f"PROMOTED {decision.version} after "
                  f"{online.learner.iterations} learner iterations "
                  f"(score {decision.active_score:.3f} -> "
                  f"{decision.candidate_score:.3f})")
            # the good swap must survive the sentinel's probe
            r = sentinel.check()
            if r is not None:
                failures.append(f"sentinel rolled back a GOOD swap: {r}")

        # ---- gate 2: degraded candidate rejected ------------------------
        cand = online.learner.snapshot(timeout=10.0)
        if cand is None:
            failures.append("no candidate snapshot for the degraded arm")
        else:
            bad = degrade_candidate(cand)
            d2 = promoter.run_once(candidate=bad)
            print(f"  degraded candidate: promoted={d2.promoted} "
                  f"reason={d2.reason} cand={d2.candidate_score}")
            if d2.promoted or d2.reason != "worse":
                failures.append(
                    f"degraded candidate not rejected as worse: {d2}")

            # ---- gate 3: forced degrade -> sentinel rollback, bitwise --
            engine = online.pool.engines[0]
            pre_params, pre_mstate = engine.committed_host()
            d3 = promoter.run_once(candidate=bad, force=True)
            if not d3.promoted or d3.reason != "forced":
                failures.append(f"force-promotion did not take: {d3}")
            else:
                reason = sentinel.check()
                print(f"  forced {d3.version}: sentinel says "
                      f"rollback={reason!r}")
                if reason != "score":
                    failures.append(
                        f"sentinel missed the forced degrade: {reason!r}")
                post_params, post_mstate = engine.committed_host()
                if not trees_equal(pre_params, post_params):
                    failures.append(
                        "post-rollback params NOT bitwise-identical")
                else:
                    print("  rollback restored bitwise-identical params")

        # ---- gate 4: warm across everything -----------------------------
        try:
            online.router.assert_warm()
            print("  assert_warm(): zero post-warmup recompiles across "
                  "promote + forced promote + rollback")
        except Exception as e:
            failures.append(f"recompile watchdog tripped: {e}")
    finally:
        publisher.stop_event.set()
        client.stop_event.set()
        publisher.join(5)
        client.join(5)
        stats = online.stats()
        online.stop()

    # ---- gate 5: the latency tail through the swaps ---------------------
    q = client.ring.quantiles((0.5, 0.99))
    p50, p99 = q.get(0.5), q.get(0.99)
    print(f"served ok={client.ok} errors={client.errors} "
          f"p50={p50 if p50 is None else round(p50 * 1e3, 1)}ms "
          f"p99={p99 if p99 is None else round(p99 * 1e3, 1)}ms "
          f"(bound {args.p99_bound * 1e3:.0f}ms); "
          f"stream batches={stats['stream']['batches']} "
          f"holdout={stats['stream']['holdout_examples']} "
          f"promotions={stats['promotion']['promotions']} "
          f"rollbacks={stats['sentinel']['rollbacks']}")
    if client.ok == 0:
        failures.append("no serve requests completed")
    if client.errors:
        failures.append(f"{client.errors} serve errors")
    if p99 is not None and p99 > args.p99_bound:
        failures.append(
            f"client p99 {p99 * 1e3:.1f}ms over the "
            f"{args.p99_bound * 1e3:.0f}ms bound")

    if failures:
        print("ONLINE SOAK FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("ONLINE SOAK PASSED")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: short window, hard asserts")
    ap.add_argument("--rate", type=float, default=20.0,
                    help="serve request rate (Hz, Poisson)")
    ap.add_argument("--publish-rate", type=float, default=8.0,
                    help="sample micro-batch publish rate (Hz, Poisson)")
    ap.add_argument("--promote-window", type=float, default=None,
                    help="seconds the gate has to promote (default: 120 "
                    "smoke, 300 soak)")
    ap.add_argument("--p99-bound", type=float, default=2.5,
                    help="client-observed p99 bound in seconds "
                    "(CPU-calibrated: training and scoring share the "
                    "cores with serving)")
    ap.add_argument("--holdout-max", type=int, default=160,
                    help="holdout reservoir bound, examples")
    ap.add_argument("--duration", type=float, default=None,
                    help="(soak) extra serve-while-train seconds before "
                    "the gates run")
    args = ap.parse_args(argv)
    if args.promote_window is None:
        args.promote_window = 120.0 if args.smoke else 300.0
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
