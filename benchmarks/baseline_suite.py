"""Fill the BASELINE.md rows the judge flagged as unmeasured.

Subcommands (each prints one JSON line):
  vgg16      — VGG16 train img/s/chip (TinyImageNet-shaped 64x64 bf16)
  inception  — imported InceptionV3 inference at the CANONICAL 299x299
  bert       — imported BERT-base inference tokens/s/chip (flash attn)
  bert_train — BERT-base-geometry native train step tokens/s/chip
  bert_finetune   — imported-BERT fine-tune tokens/s (grafted head)
  inception_train — imported-InceptionV3 fine-tune img/s (299x299)
  word2vec   — SGNS + HS tokens/s at 100k vocab (corpus-shaped workload)
               [--pairgen=auto|numpy|legacy selects the producer]
  lstm       — TextGenerationLSTM train tokens/s (2xLSTM-512; [f32|bf16])
  doc2vec_producer — DBOW host pair-generation rate, dispatch no-op'd;
               --native-ab [--smoke] instead runs the native-vs-fallback
               A/B gate (native >= fallback tokens/s AND bitwise-equal
               dispatch streams; exits 1 on violation)

Run: python benchmarks/baseline_suite.py <subcommand>
"""

import json
import sys
import time

import numpy as np


def _sync(x):
    return float(np.asarray(x).ravel()[0])


def vgg16():
    import jax.numpy as jnp
    import jax.random as jrandom
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.zoo.models import VGG16

    batch, k, n = 512, 48, 2
    model = VGG16(num_classes=200, height=64, width=64, channels=3,
                  compute_dtype="bfloat16").init()

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
        # VGG16 is a MultiLayerNetwork: _loss takes raw arrays
        return model._loss(params, mstate, feats, labels, fmask,
                           lmask, rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)).astype(np.float32))
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), rng.integers(0, 200, batch)] = 1.0
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 200))
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    _sync(losses[-1])
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    _sync(losses[-1])
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "vgg16_64x64_bf16_train_images_per_sec",
                      "value": round(n * k * batch / dt, 1),
                      "unit": "images/sec/chip"}))


def inception():
    import jax
    import jax.numpy as jnp
    import keras
    from deeplearning4j_tpu.modelimport.keras import (
        import_keras_model_and_weights)
    import tempfile, os

    km = keras.applications.InceptionV3(weights=None,
                                        input_shape=(299, 299, 3),
                                        classes=1000)
    fd, p = tempfile.mkstemp(suffix=".h5")
    os.close(fd)
    try:
        km.save(p)
        model = import_keras_model_and_weights(p)
    finally:
        os.unlink(p)

    batch, k, n = 128, 8, 3
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 299, 299, 3)).astype(np.float32))
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    params = model.train_state.params
    mstate = model.train_state.model_state

    def fwd_many(params, mstate, xs):
        def one(_, xk):
            inputs = {model.conf.network_inputs[0]: xk}
            acts, _ = model._walk(params, mstate, inputs,
                                  {"__default__": None}, False, None,
                                  stop_before_loss=False)
            out = acts[model.conf.network_outputs[0]]
            return None, jnp.sum(out)
        _, sums = jax.lax.scan(one, None, xs)
        return sums[-1]

    jf = jax.jit(fwd_many)
    _sync(jf(params, mstate, xs))
    t0 = time.perf_counter()
    for _ in range(n):
        s = jf(params, mstate, xs)
    _sync(s)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "inception_v3_299x299_f32_infer_images_per_sec",
        "value": round(n * k * batch / dt, 1),
        "unit": "images/sec/chip"}))


def bert():
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.modelimport.bert import (
        BERT_BASE, example_inputs, import_bert_base)

    seq, batch, k, n = 128, 64, 4, 3
    model, _km = import_bert_base(seq_len=seq)
    ids, pos = example_inputs(batch, seq, BERT_BASE["vocab"])
    ids = jnp.asarray(ids)
    pos = jnp.asarray(pos)
    idss = jnp.broadcast_to(ids, (k,) + ids.shape)
    poss = jnp.broadcast_to(pos, (k,) + pos.shape)
    params = model.train_state.params
    mstate = model.train_state.model_state

    def fwd_many(params, mstate, idss, poss):
        def one(_, xk):
            i, p = xk
            inputs = dict(zip(model.conf.network_inputs, (i, p)))
            acts, _ = model._walk(params, mstate, inputs,
                                  {"__default__": None}, False, None,
                                  stop_before_loss=False)
            return None, jnp.sum(acts[model.conf.network_outputs[0]])
        _, sums = jax.lax.scan(one, None, (idss, poss))
        return sums[-1]

    jf = jax.jit(fwd_many)
    _sync(jf(params, mstate, idss, poss))
    t0 = time.perf_counter()
    for _ in range(n):
        s = jf(params, mstate, idss, poss)
    _sync(s)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bert_base_seq128_infer_tokens_per_sec",
        "value": round(n * k * batch * seq / dt, 1),
        "unit": "tokens/sec/chip"}))


def bert_train():
    """Native BERT-base-geometry training throughput: 12 blocks, width
    768, MLM-style dense head, bf16 compute, flash attention."""
    import jax.numpy as jnp
    import jax.random as jrandom
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.attention import (
        LearnedPositionalEmbedding, TransformerEncoderBlock)
    from deeplearning4j_tpu.nn.layers.feedforward import (
        EmbeddingSequenceLayer)
    from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Adam

    vocab, width, seq, batch, k, n = 30522, 768, 128, 32, 4, 3
    b = (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-4))
         .compute_dtype("bfloat16").list()
         .layer(EmbeddingSequenceLayer(n_in=vocab, n_out=width))
         .layer(LearnedPositionalEmbedding(max_len=seq)))
    for _ in range(12):
        b = b.layer(TransformerEncoderBlock(n_out=width, n_heads=12,
                                            ffn_mult=4))
    conf = (b.layer(RnnOutputLayer(n_out=vocab))
            .set_input_type(InputType.recurrent(1, seq)).build())
    model = MultiLayerNetwork(conf).init()

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
        return model._loss(params, mstate, feats, labels, fmask, lmask,
                           rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, (batch, seq)).astype(np.float32)
    lab = np.zeros((batch, seq, vocab), np.float32)
    lab[np.arange(batch)[:, None], np.arange(seq)[None, :],
        rng.integers(0, vocab, (batch, seq))] = 1.0
    xs = jnp.broadcast_to(jnp.asarray(toks), (k, batch, seq))
    ys = jnp.broadcast_to(jnp.asarray(lab), (k, batch, seq, vocab))
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    _sync(losses[-1])
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    _sync(losses[-1])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bert_base_seq128_bf16_train_tokens_per_sec",
        "value": round(n * k * batch * seq / dt, 1),
        "unit": "tokens/sec/chip"}))


def build_inception_finetune(batch: int = 64, k: int = 8):
    """The canonical imported-InceptionV3 fine-tune setup (BASELINE
    config 3's training half): import the Keras graph, swap the
    1000-way head for 200 classes via TransferLearning.GraphBuilder,
    train the WHOLE network (fwd+bwd+Adam) with K scanned steps per
    dispatch. Shared by ``inception_train`` and ``profile_hw.py
    inception`` so the profiler measures the EXACT graph the benchmark
    ships. Returns ``(model, steps_fn, xs, ys)``."""
    import jax.numpy as jnp
    import keras
    import os
    import tempfile

    from deeplearning4j_tpu.modelimport.keras import (
        import_keras_model_and_weights)
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration, TransferLearning)
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Adam

    km = keras.applications.InceptionV3(weights=None,
                                        input_shape=(299, 299, 3),
                                        classes=1000)
    fd, p = tempfile.mkstemp(suffix=".h5")
    os.close(fd)
    try:
        km.save(p)
        model = import_keras_model_and_weights(p)
    finally:
        os.unlink(p)

    head = model.conf.network_outputs[0]
    # bf16 fine-tune dtype (round 5): params stay f32, convs run at MXU
    # rate — 725.5 (f32) -> 1,175.6 img/s measured, same harness
    model = (TransferLearning.GraphBuilder(model)
             .fine_tune_configuration(
                 FineTuneConfiguration.Builder().updater(Adam(1e-4))
                 .compute_dtype("bfloat16").build())
             .n_out_replace(head, 200)
             .build())

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 299, 299, 3))
                    .astype(np.float32))
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), rng.integers(0, 200, batch)] = 1.0
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 200))

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng_, it):
        return model._loss(params, mstate, (feats,), (labels,), fmask,
                           lmask, rng_, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    return model, steps_fn, xs, ys


def inception_train():
    """Imported-InceptionV3 FINE-TUNE throughput — see
    build_inception_finetune."""
    import jax.random as jrandom

    batch, k, n = 64, 8, 3
    model, steps_fn, xs, ys = build_inception_finetune(batch, k)
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    _sync(losses[-1])
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    _sync(losses[-1])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "imported_inception_v3_299x299_finetune_images_per_sec",
        "value": round(n * k * batch / dt, 1),
        "unit": "images/sec/chip"}))


def build_bert_finetune(seq: int = 128, batch: int = 128, k: int = 16,
                        dtype: str = "bf16"):
    """The canonical imported-BERT fine-tune setup (BASELINE config 3
    training half): graft a mean-pool + 2-class head on the imported
    encoder via TransferLearning.GraphBuilder — the reference's flagship
    Keras-import workflow (KerasModelImport.java:41 → TransferLearning).

    Shared by ``bert_finetune`` and ``profile_hw.py bert`` so the
    profiler measures the EXACT graph the benchmark ships. Returns
    ``(ft, steps_fn, (idss, poss), ys)``.

    bf16 compute via FineTuneConfiguration (round 5): imported params
    stay f32, activations/matmuls run at MXU rate. Batch 128 (vs 32)
    keeps every matmul MXU-shaped; attention dispatches to the plain
    XLA path at seq 128 (measured crossover, benchmarks/attn_crossover).
    """
    import jax.numpy as jnp
    from deeplearning4j_tpu.modelimport.bert import (
        BERT_BASE, example_inputs, import_bert_base)
    from deeplearning4j_tpu.nn.layers.output import (
        GlobalPoolingLayer, OutputLayer, PoolingType)
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration, TransferLearning)
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Adam

    model, _km = import_bert_base(seq_len=seq)
    enc_out = model.conf.network_outputs[0]
    ftc = FineTuneConfiguration.Builder().updater(Adam(2e-5))
    if dtype == "bf16":
        ftc = ftc.compute_dtype("bfloat16")
    ft = (TransferLearning.GraphBuilder(model)
          .fine_tune_configuration(ftc.build())
          .add_layer("pool",
                     GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                     enc_out)
          .add_layer("cls", OutputLayer(n_out=2), "pool")
          .set_outputs("cls")
          .build())

    rng = np.random.default_rng(0)
    ids, pos = example_inputs(batch, seq, BERT_BASE["vocab"])
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch)]
    idss = jnp.broadcast_to(jnp.asarray(ids), (k,) + ids.shape)
    poss = jnp.broadcast_to(jnp.asarray(pos), (k,) + pos.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 2))

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng_, it):
        return ft._loss(params, mstate, feats, labels, fmask, lmask,
                        rng_, it)

    # bf16 shadow params carried through the scan (round 6): kills the
    # per-step f32→bf16 recast at the top of the loss (~6.8 ms/step
    # measured in PERF_ANALYSIS r5) — the cast rides the optimizer
    # update's epilogue instead. Bit-identical numerics.
    shadow = None
    if dtype == "bf16":
        from deeplearning4j_tpu.models.base import cast_params
        shadow = lambda p: cast_params(p, "bfloat16")
    steps_fn = make_scan_train_step(loss_fn, ft._tx, shadow_cast=shadow)
    return ft, steps_fn, (idss, poss), ys


def bert_finetune():
    """Imported-BERT-base FINE-TUNE tokens/s — see build_bert_finetune."""
    import jax.random as jrandom

    seq, batch, k, n = 128, 128, 16, 3
    ft, steps_fn, feats, ys = build_bert_finetune(seq, batch, k)
    key = jrandom.PRNGKey(0)
    ts = ft.train_state
    ts, losses = steps_fn(ts, feats, (ys,), None, None, key)
    _sync(losses[-1])
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, feats, (ys,), None, None,
                              jrandom.fold_in(key, i))
    _sync(losses[-1])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "imported_bert_base_seq128_finetune_tokens_per_sec",
        "value": round(n * k * batch * seq / dt, 1),
        "unit": "tokens/sec/chip"}))


def build_textgen_lstm(units: int = 512, seq: int = 128,
                       batch: int = 256, k: int = 16,
                       dtype: str = "f32", vocab: int = 77):
    """The BASELINE TextGenerationLSTM throughput config (scaled
    geometry: 2×LSTM-``units``, one-hot vocab inputs, RnnOutputLayer) —
    shared by the ``lstm`` bench and ``profile_hw.py lstm`` so the
    profiler measures the exact benchmarked graph."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.multi_layer_network import (
        MultiLayerNetwork)
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.inputs import InputType
    from deeplearning4j_tpu.nn.layers.recurrent import LSTM
    from deeplearning4j_tpu.nn.layers.output import RnnOutputLayer
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Adam

    # matches zoo TextGenerationLSTM.conf() incl. the gradient clip the
    # named model ships with — scaled geometry only
    b = (NeuralNetConfiguration.Builder().seed(123).updater(Adam(2e-3))
         .gradient_normalization("clip_value", 5.0))
    if dtype == "bf16":
        b = b.compute_dtype("bfloat16")
    conf = (b.list()
            .layer(LSTM(n_out=units))
            .layer(LSTM(n_out=units))
            .layer(RnnOutputLayer(n_out=vocab))
            .set_input_type(InputType.recurrent(vocab, seq))
            .build())
    model = MultiLayerNetwork(conf).init()

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
        return model._loss(params, mstate, feats, labels, fmask, lmask,
                           rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq))
    x = np.eye(vocab, dtype=np.float32)[ids]          # (N, T, vocab)
    nxt = np.roll(ids, -1, axis=1)
    y = np.eye(vocab, dtype=np.float32)[nxt]
    xs = jnp.broadcast_to(jnp.asarray(x), (k,) + x.shape)
    ys = jnp.broadcast_to(jnp.asarray(y), (k, ) + y.shape)
    # prime model_state (the LSTM layers add last_h/last_c on first
    # apply; the K-step scan needs carry-in == carry-out structure) —
    # forward-only jit, much cheaper to compile than a full train step
    import jax
    import jax.random as jrandom
    _, ms = jax.jit(loss_fn)(
        model.train_state.params, model.train_state.model_state,
        jnp.asarray(x), jnp.asarray(y), None, None,
        jrandom.PRNGKey(99), model.train_state.iteration)
    model.train_state = model.train_state._replace(model_state=ms)
    return model, steps_fn, xs, ys


def lstm():
    """TextGenerationLSTM train throughput (BASELINE config: 2×LSTM-512,
    T=128, batch 256). Optional argv: dtype f32|bf16."""
    import jax.random as jrandom

    dtype = sys.argv[2] if len(sys.argv) > 2 else "f32"
    if dtype not in ("f32", "bf16"):
        sys.exit(f"unknown dtype {dtype!r}: expected f32|bf16")
    seq, batch, k, n = 128, 256, 16, 3
    model, steps_fn, xs, ys = build_textgen_lstm(
        seq=seq, batch=batch, k=k, dtype=dtype)
    key = jrandom.PRNGKey(0)
    ts = model.train_state
    ts, losses = steps_fn(ts, xs, ys, None, None, key)
    _sync(losses[-1])
    t0 = time.perf_counter()
    for i in range(n):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    _sync(losses[-1])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": f"textgen_lstm512_seq128_{dtype}_train_tokens_per_sec",
        "value": round(n * k * batch * seq / dt, 1),
        "unit": "tokens/sec/chip"}))


def word2vec():
    """SGNS and HS at 100k vocab on a zipf-shaped corpus (the scale the
    reference's native AggregateSkipGram targets — SkipGram.java:176)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    v, n_tokens = 100_000, 3_000_000
    pairgen = "auto"
    for a in sys.argv[2:]:
        if a.startswith("--pairgen="):
            pairgen = a.split("=", 1)[1]
    rng = np.random.default_rng(0)
    # zipf-ish draw over a 100k vocab, chunked into 40-token sentences
    freq = 1.0 / np.arange(1, v + 1) ** 1.05
    freq /= freq.sum()
    tokens = rng.choice(v, size=n_tokens, p=freq)
    words = np.char.add("w", tokens.astype("U7"))
    seqs = [words[i:i + 40].tolist() for i in range(0, n_tokens, 40)]

    for label, kw in (("sgns", {}),
                      ("hs", {"use_hierarchic_softmax": True}),
                      ("cbow", {"use_cbow": True})):
        # 64k-pair scanned superchunks (8 chunks/dispatch) amortize the
        # ~26 ms tunnel overhead; warm = steady-state throughput, cold =
        # warm + the one-off XLA compile (cached for the process)
        times = []          # drained e2e (honest through the tunnel)
        pipe_times = []     # fit-return (the host/producer pipeline rate)
        for _trial in range(2):
            model = Word2Vec(layer_size=128, window_size=5, negative=5,
                             min_word_frequency=1, epochs=1,
                             batch_size=65536, seed=3, pairgen=pairgen,
                             **kw)
            model.build_vocab(seqs)
            t0 = time.perf_counter()
            model.fit(seqs)
            pipe_times.append(time.perf_counter() - t0)
            # drain the async device queue INSIDE the timer (round-5
            # methodology fix): fit() returns with dispatches queued,
            # and through the tunneled transport the per-superchunk
            # input transfers (~4.2 MB at a measured ~35 MB/s) dominate
            # that queue — excluding the tail overstated e2e. The
            # pipeline rate is reported too: it is what a PCIe-attached
            # host (GB/s transfers) would sustain, where host pair
            # generation (~1.5M tokens/s) is the real bound. Drain via
            # a 4-byte element read — np.asarray(syn0) would pull the
            # whole ~50 MB table back through the same slow tunnel
            # INSIDE the timer.
            _sync(model.syn0[0, 0])
            times.append(time.perf_counter() - t0)
        print(json.dumps({
            "metric": f"word2vec_{label}_100kvocab_tokens_per_sec",
            "value": round(n_tokens / times[1], 1),
            "cold_value": round(n_tokens / times[0], 1),
            "pipeline_value": round(n_tokens / pipe_times[1], 1),
            "unit": "tokens/sec (warm, device-drained; pipeline_value ="
                    " fit-return rate, the non-tunnel bound)",
            "pairgen": pairgen,
            "vocab": int(model.vocab.num_words())}))


def doc2vec_producer():
    """DBOW host pair-generation rate (the r5 measured bound: 249k
    tokens/s fit-return, "per-doc host pairgen bound") at the r5
    geometry — 20k docs × 100 tokens, 50k vocab. Device dispatch is
    no-op'd so both numbers isolate the HOST producer: the round-6
    corpus-level walk (_window_slabs + per-slot label gathers,
    ``pairgen="legacy"`` pinned for metric continuity) vs the r5
    per-doc loop it replaced (inlined here as the baseline).

    ``--native-ab`` runs the round-11 CI gate instead: interleaved
    native-vs-numpy A/B of the FUSED producer (nlp/pairgen.py), failing
    (exit 1) unless native >= fallback tokens/s AND both arms hand the
    device a bitwise-identical dispatch stream (sha256 over every prep
    array). ``--smoke`` shrinks the geometry for the runtests.sh tier."""
    from deeplearning4j_tpu.nlp import skipgram as sk
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
    from deeplearning4j_tpu.nlp.sentence_iterators import LabelledDocument
    from deeplearning4j_tpu.nlp.sequence_vectors import _PairStream

    native_ab = "--native-ab" in sys.argv[2:]
    if "--smoke" in sys.argv[2:]:
        v, n_docs, doc_len = 5_000, 2_000, 60
    else:
        v, n_docs, doc_len = 50_000, 20_000, 100
    rng = np.random.default_rng(0)
    freq = 1.0 / np.arange(1, v + 1) ** 1.05
    freq /= freq.sum()
    tokens = rng.choice(v, size=n_docs * doc_len, p=freq)
    words = np.char.add("w", tokens.astype("U7"))
    docs = [LabelledDocument(" ".join(words[i * doc_len:(i + 1) * doc_len]),
                             [f"DOC_{i}"]) for i in range(n_docs)]
    n_tokens = n_docs * doc_len

    def per_doc_produce(pv, tokenized, total, chunk):
        # the r5 producer this round replaced — per-doc numpy
        stream = _PairStream(pv, chunk, total, sink=lambda prep: None)
        W = pv.window_size
        for _ep in range(pv.epochs):
            for toks, labels in tokenized:
                idxs = np.asarray(pv._indices(toks), np.int32)
                lidxs = np.asarray(
                    [i for i in (pv.vocab.index_of(lb) for lb in labels)
                     if i >= 0], np.int32)
                n = len(idxs)
                if n and len(lidxs):
                    stream.push(np.repeat(lidxs, n),
                                np.tile(idxs, len(lidxs)))
                    stream.seen += len(lidxs) * n
                if n >= 2:
                    grid, valid = sk.window_grid(n, W, pv._rng)
                    stream.push(np.repeat(idxs, valid.sum(axis=1)),
                                idxs[grid[valid]])
                stream.seen += n
        stream.finish()

    def make_pv(pairgen):
        pv = ParagraphVectors(dm=False, layer_size=128, window_size=5,
                              negative=5, min_word_frequency=1, epochs=1,
                              batch_size=65536, seed=3,
                              overlap_pairgen=False, pairgen=pairgen)
        tokenized = [(d.content.split(), d.labels) for d in docs]
        pv._label_set = {lb for _t, lbs in tokenized for lb in lbs}
        pv.build_vocab([t for t, _ in tokenized],
                       special_tokens=sorted(pv._label_set))
        pv._init_tables()
        pv._dispatch_chunks = lambda prep: None   # host producer only
        return pv, tokenized

    if native_ab:
        _doc2vec_native_ab(make_pv, n_tokens)
        return

    out = {}
    for label in ("corpus_level", "per_doc_r5"):
        pv, tokenized = make_pv("legacy")
        total = max(1, n_tokens * 2)
        best = np.inf
        for _trial in range(2):
            t0 = time.perf_counter()
            if label == "corpus_level":
                pv._fit_fast_dbow(tokenized, total)
            else:
                chunk = pv._pair_chunk_size(
                    (total // 2) * (pv.window_size + 2))
                per_doc_produce(pv, tokenized, total, chunk)
            best = min(best, time.perf_counter() - t0)
        out[label] = n_tokens / best
    print(json.dumps({
        "metric": "doc2vec_dbow_host_producer_tokens_per_sec",
        "value": round(out["corpus_level"], 1),
        "per_doc_r5_value": round(out["per_doc_r5"], 1),
        "speedup": round(out["corpus_level"] / out["per_doc_r5"], 2),
        "unit": "tokens/sec (host pair generation only, dispatch "
                "no-op'd; 20k docs x 100 tokens, 50k vocab)"}))


def _doc2vec_native_ab(make_pv, n_tokens):
    """The --native-ab gate body: bitwise stream equality (one hashed
    pass per arm) then interleaved best-of-2 timing with the dispatch
    no-op'd. Skips cleanly (exit 0) when the native library is absent —
    runtests.sh runs this tier only after a successful build, but a
    toolchain-less checkout must still pass the suite."""
    import hashlib
    from deeplearning4j_tpu.utils import native as native_lib

    if not native_lib.pairgen_available():
        print(json.dumps({"metric": "doc2vec_producer_native_ab",
                          "skipped": "native pairgen unavailable"}))
        return
    total = max(1, n_tokens * 2)
    arms = {}
    for pairgen in ("auto", "numpy"):
        pv, tokenized = make_pv(pairgen)
        h = hashlib.sha256()

        def hash_sink(prep, _h=h):
            for a in prep[1:]:
                _h.update(np.ascontiguousarray(a).tobytes())
        pv._dispatch_chunks = hash_sink
        pv._fit_fast_dbow(tokenized, total)
        pv._dispatch_chunks = lambda prep: None
        arms[pairgen] = (pv, tokenized, h.hexdigest())
    best = {p: np.inf for p in arms}
    for _trial in range(2):              # interleaved A/B
        for p, (pv, tokenized, _hx) in arms.items():
            t0 = time.perf_counter()
            pv._fit_fast_dbow(tokenized, total)
            best[p] = min(best[p], time.perf_counter() - t0)
    rate = {p: n_tokens / best[p] for p in best}
    bitwise_equal = arms["auto"][2] == arms["numpy"][2]
    ok = bitwise_equal and rate["auto"] >= rate["numpy"]
    print(json.dumps({
        "metric": "doc2vec_producer_native_ab",
        "native_tokens_per_sec": round(rate["auto"], 1),
        "fallback_tokens_per_sec": round(rate["numpy"], 1),
        "speedup": round(rate["auto"] / rate["numpy"], 2),
        "bitwise_equal": bitwise_equal,
        "ok": ok,
        "unit": "tokens/sec (fused producer, dispatch no-op'd)"}))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    globals()[sys.argv[1]]()
