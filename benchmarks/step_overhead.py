"""Isolate fixed per-call overhead in the ResNet50 train step.

Compares per-step time for batch 512/1024/2048 and for a k-step
lax.scan-fused loop (one dispatch for k optimizer steps, batches staged
on device). If step(batch)/img is flat while scan wins, the gap is
host-dispatch overhead, not device work.
"""

import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import jax.random as jrandom
    import optax

    from deeplearning4j_tpu.optimize.updaters import Nesterovs
    from deeplearning4j_tpu.optimize.solver import TrainState
    from deeplearning4j_tpu.zoo.models import ResNet50

    model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype="bfloat16",
                     updater=Nesterovs(1e-2, 0.9)).init()
    tx = model._tx
    key = jrandom.PRNGKey(0)
    rng = np.random.default_rng(0)

    def data(b):
        x = jnp.asarray(rng.normal(size=(b, 64, 64, 3)).astype(np.float32))
        idx = rng.integers(0, 200, b)
        y = np.zeros((b, 200), np.float32)
        y[np.arange(b), idx] = 1.0
        return x, jnp.asarray(y)

    # ---- per-call step at several batch sizes ---------------------------
    for b in (512, 1024, 2048):
        m = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype="bfloat16",
                     updater=Nesterovs(1e-2, 0.9)).init()
        step = m._build_train_step()
        x, y = data(b)
        ts = m.train_state
        for i in range(3):
            ts, loss = step(ts, (x,), (y,), None, None,
                            jrandom.fold_in(key, i))
        float(loss)
        t0 = time.perf_counter()
        n = 20
        for i in range(n):
            ts, loss = step(ts, (x,), (y,), None, None,
                            jrandom.fold_in(key, 100 + i))
        float(loss)
        dt = (time.perf_counter() - t0) / n
        print(f"batch {b:5d}: {dt * 1e3:7.2f} ms/step "
              f"({b / dt:10,.0f} img/s)")

    # ---- k-step scan inside one dispatch --------------------------------
    m = ResNet50(num_classes=200, height=64, width=64, channels=3,
                 compute_dtype="bfloat16",
                 updater=Nesterovs(1e-2, 0.9)).init()
    b, k = 1024, 8
    x, y = data(b)
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(y, (k,) + y.shape)

    def scan_steps(ts, xs, ys, rng):
        def one(ts, inp):
            xk, yk, i = inp
            def lf(p):
                return m._loss(p, ts.model_state, (xk,), (yk,), None,
                               None, jax.random.fold_in(rng, i),
                               ts.iteration)
            (loss, new_ms), grads = jax.value_and_grad(
                lf, has_aux=True)(ts.params)
            updates, new_opt = tx.update(grads, ts.opt_state, ts.params)
            new_params = optax.apply_updates(ts.params, updates)
            return TrainState(new_params, new_ms, new_opt,
                              ts.iteration + 1), loss
        ts, losses = jax.lax.scan(one, ts, (xs, ys, jnp.arange(k)))
        return ts, losses[-1]

    jscan = jax.jit(scan_steps, donate_argnums=(0,))
    ts = m.train_state
    for i in range(2):
        ts, loss = jscan(ts, xs, ys, jrandom.fold_in(key, i))
    float(loss)
    t0 = time.perf_counter()
    n = 5
    for i in range(n):
        ts, loss = jscan(ts, xs, ys, jrandom.fold_in(key, 50 + i))
    float(loss)
    dt = (time.perf_counter() - t0) / (n * k)
    print(f"scan k={k}, batch {b}: {dt * 1e3:7.2f} ms/step "
          f"({b / dt:10,.0f} img/s)")


if __name__ == "__main__":
    main()
