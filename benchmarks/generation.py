"""Generative serving: continuous-batching decode soak + $/token A/B.

The claims under test (generation/engine.py):

- **correctness**: continuous batching is a pure scheduling trick — a
  sequence decoded in a shared slot batch, with other sequences joining
  and retiring around it mid-flight, must be BITWISE identical to the
  same sequence decoded alone through the model's own ``rnn_time_step``
  reference path (greedy), and a seeded sampling run must reproduce
  exactly. The masked-neutral tick makes co-residents invisible; this
  bench proves it end to end, through the HTTP streaming surface.
- **compile discipline**: the AOT bucket ladder means a soak with
  mid-stream join/leave, slot reuse and bucket resizes performs ZERO
  live compiles after warmup (watchdog-asserted).
- **$/token**: decode is memory-bound on the dense head (re-read every
  tick), so the int8 head must move strictly fewer bytes/token than
  bf16 while agreeing with the f32 head's next-token choice within the
  quant-gate budget — measured on the committed pretrained
  TextGenerationLSTM artifact, not a toy.

Load shape: ``--sequences`` clients with Poisson staggered arrivals,
each streaming ``POST /api/generate`` (SSE) through a FleetRouter-
fronted UIServer — the exact production path ``serve --generate``
wires. More sequences than slots forces mid-flight slot reuse.

Usage:
    python benchmarks/generation.py            # full soak + A/B table
    python benchmarks/generation.py --smoke    # CI gate: parity, zero
        # post-warmup recompiles, token p99 + TTFT bounds, int8 head
        # within budget and strictly fewer bytes/token than bf16
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
import urllib.error
import urllib.request

from deeplearning4j_tpu.generation import (GenerationEngine,
                                           head_bytes_per_token,
                                           reference_decode)
from deeplearning4j_tpu.observe.registry import MetricsRegistry

SMALL_VOCAB = 31


def small_model():
    """Tiny TextGenerationLSTM geometry: fast ticks, same 3-layer
    stacked-LSTM + dense-head structure as the committed artifact."""
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    m = TextGenerationLSTM()
    m.lstm_units = 32
    m.vocab_size = SMALL_VOCAB
    m.timesteps = 8
    return m.init()


def pretrained_model():
    """The committed artifact (checksummed resource weights) — the
    $/token A/B needs real peaked distributions, not toy babble."""
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    return TextGenerationLSTM().init_pretrained()


# ---- parity: join/leave invisibility + seeded reproducibility ------------


def run_parity(args, failures) -> None:
    """Greedy decode under staggered join/leave must match the
    single-sequence reference bitwise; seeded sampling must reproduce
    exactly and differ across seeds."""
    model = small_model()
    rng = random.Random(1234)
    n = 8 if args.smoke else 16
    cfgs = []
    for _ in range(n):
        prompt = [rng.randrange(SMALL_VOCAB)
                  for _ in range(rng.randrange(3, 9))]
        cfgs.append((prompt, rng.randrange(12, 40)))
    refs = [reference_decode(model, p, m) for p, m in cfgs]

    eng = GenerationEngine(model, max_slots=4,
                           registry=MetricsRegistry(),
                           session_id="gen-parity")
    try:
        streams = []
        for i, (prompt, max_new) in enumerate(cfgs):
            streams.append(eng.submit(prompt, max_new_tokens=max_new,
                                      greedy=True))
            if i >= 4:      # burst fills the slots; the rest queue and
                time.sleep(rng.random() * 0.003)    # join mid-flight
        mismatch = 0
        for i, (s, ref) in enumerate(zip(streams, refs)):
            got = s.result(timeout=120.0)["ids"]
            if got != ref:
                mismatch += 1
                failures.append(
                    f"parity: sequence {i} diverged from reference "
                    f"decode (first 8: got {got[:8]} want {ref[:8]})")
        st = eng.stats()
        if st["slots"]["max_active"] < 2:
            failures.append(
                "parity: sequences never overlapped in the slot batch "
                "— join/leave was not exercised")
        a = eng.generate(cfgs[0][0], greedy=False, seed=7,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        b = eng.generate(cfgs[0][0], greedy=False, seed=7,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        c = eng.generate(cfgs[0][0], greedy=False, seed=8,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        if a["ids"] != b["ids"]:
            failures.append("parity: seed 7 did not reproduce itself")
        if a["ids"] == c["ids"]:
            failures.append("parity: seeds 7 and 8 sampled identical "
                            "sequences")
        try:
            eng.assert_warm()
        except Exception as e:
            failures.append(f"parity engine not warm: {e}")
        print(f"parity: {n - mismatch}/{n} staggered sequences bitwise-"
              f"equal to reference (max co-resident "
              f"{st['slots']['max_active']}), seeded sampling "
              f"reproducible")
    finally:
        eng.shutdown()


# ---- soak: Poisson SSE streams through the fleet front door --------------


def _stream_one(url, payload, timeout=300.0):
    """One SSE client: POST /api/generate, read data: events as they
    arrive (HTTP/1.0 stream, EOF-delimited). Returns ids + the terminal
    event + client-observed TTFT."""
    req = urllib.request.Request(
        url + "/api/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    ids, terminal, ttft_ms = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:"):
                continue
            ev = json.loads(line[5:].strip())
            if "token" in ev:
                if ttft_ms is None:
                    ttft_ms = (time.perf_counter() - t0) * 1e3
                ids.append(ev["token"])
            else:
                terminal = ev
    return {"ids": ids, "terminal": terminal, "ttft_ms": ttft_ms}


def run_soak(args, failures) -> None:
    """>= ``--sequences`` sequences, Poisson staggered arrivals, each a
    streamed ``POST /api/generate`` through FleetRouter admission.
    Gates: every stream completes, every greedy output bitwise-equal to
    the sequential reference decode, slots reused mid-flight (more
    sequences than slots, co-residency observed), zero live compiles
    after warmup, token p99 / TTFT under the CPU bounds."""
    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    from deeplearning4j_tpu.ui.generation_module import GenerationModule
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    model = small_model()
    rng = random.Random(args.seed)
    n = args.sequences
    cfgs = []
    for _ in range(n):
        prompt = [rng.randrange(SMALL_VOCAB)
                  for _ in range(rng.randrange(4, 12))]
        cfgs.append((prompt, rng.randrange(64, 129)))
    refs = [reference_decode(model, p, m) for p, m in cfgs]

    engine = GenerationEngine(model, max_slots=args.max_slots,
                              max_new_tokens=256, session_id="gen-soak")
    fleet = FleetRouter(session_id="gen-soak")
    fleet.add_generation_pool("gen", engine,
                              slo_token_ms=args.slo_token_ms)
    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(GenerationModule(router=fleet, model="gen"))
    server.start()
    try:
        fleet.assert_warm()             # warm BEFORE traffic
        results = [None] * n
        errors = []

        def client(i, prompt, max_new):
            try:
                results[i] = _stream_one(
                    server.url, {"prompt": prompt,
                                 "max_new_tokens": max_new,
                                 "greedy": True, "stream": True})
            except urllib.error.HTTPError as e:
                e.read()
                errors.append(f"sequence {i}: HTTP {e.code}")
            except Exception as e:
                errors.append(f"sequence {i}: {e}")

        threads = []
        t_start = time.perf_counter()
        for i, (prompt, max_new) in enumerate(cfgs):
            t = threading.Thread(target=client,
                                 args=(i, prompt, max_new))
            t.start()
            threads.append(t)
            time.sleep(rng.expovariate(args.rate))  # Poisson arrivals
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t_start

        failures.extend(f"soak: {e}" for e in errors)
        mismatch = 0
        ttfts = []
        for i, (res, ref) in enumerate(zip(results, refs)):
            if res is None:
                continue
            if res["terminal"] is None or "error" in (res["terminal"]
                                                      or {}):
                failures.append(
                    f"soak: sequence {i} stream ended without a done "
                    f"event ({res['terminal']})")
            if res["ids"] != ref:
                mismatch += 1
                failures.append(
                    f"soak: sequence {i} streamed ids diverged from "
                    f"the sequential reference decode")
            if res["ttft_ms"] is not None:
                ttfts.append(res["ttft_ms"])

        st = engine.stats()
        retired = sum(st["sequences"]["retired"].values())
        tok_p99 = st["latency_ms"]["token"].get("p99", 0.0)
        ttft_p99 = st["latency_ms"]["ttft"].get("p99", 0.0)
        print(f"soak: {n} sequences Poisson {args.rate:.0f}/s over "
              f"{args.max_slots} slots in {wall:.1f}s — "
              f"{st['tokens']['generated']} tokens, max co-resident "
              f"{st['slots']['max_active']}, retired {retired}")
        print(f"  engine: token p50="
              f"{st['latency_ms']['token'].get('p50', 0.0):.2f}ms "
              f"p99={tok_p99:.2f}ms  ttft p99={ttft_p99:.1f}ms  "
              f"client ttft max="
              f"{max(ttfts) if ttfts else 0.0:.1f}ms")
        if mismatch == 0 and not errors:
            print(f"  all {n} streamed outputs bitwise-equal to "
                  "reference")

        if retired < n:
            failures.append(f"soak: only {retired}/{n} sequences "
                            "retired")
        if st["slots"]["max_active"] > args.max_slots:
            failures.append("soak: active slots exceeded the bucket")
        if st["slots"]["max_active"] < 2:
            failures.append(
                "soak: sequences never co-resided — mid-flight "
                "join/leave was not exercised")
        if n <= args.max_slots:
            failures.append(
                f"soak: {n} sequences cannot prove slot reuse over "
                f"{args.max_slots} slots — raise --sequences")
        if tok_p99 > args.token_p99_ms:
            failures.append(
                f"soak: token p99 {tok_p99:.2f}ms over the "
                f"{args.token_p99_ms:.0f}ms bound")
        if ttft_p99 > args.ttft_ms:
            failures.append(
                f"soak: TTFT p99 {ttft_p99:.1f}ms over the "
                f"{args.ttft_ms:.0f}ms bound")
        try:
            engine.assert_warm()        # zero live compiles under soak
            fleet.assert_warm()
        except Exception as e:
            failures.append(f"soak: not warm after traffic: {e}")
        with urllib.request.urlopen(server.url + "/metrics") as r:
            metrics = r.read().decode()
        if "dl4j_gen_tokens_total" not in metrics:
            failures.append("soak: dl4j_gen_* series missing from "
                            "/metrics")
    finally:
        server.stop()
        fleet.shutdown()


# ---- $/token A/B: f32 / bf16 / int8 head on the committed artifact -------


def run_token_ab(args, failures) -> None:
    """Per-precision decode arms over the pretrained artifact. The $
    proxy is head bytes/token — decode re-reads the dense head every
    tick, so its resident bytes ARE the per-token memory traffic
    quantization buys down. Gates: int8 strictly fewer bytes/token than
    bf16 at >= ``--agreement`` next-token agreement vs f32 (the
    decode-level quant gate, enforced again here), every arm warm."""
    from deeplearning4j_tpu.evaluation.quant_gate import QuantGateError

    model = pretrained_model()
    prompt = "The quick brown fox "
    max_new = 64 if args.smoke else 256
    rows = {}
    for arm in ("f32", "bf16", "int8"):
        try:
            eng = GenerationEngine(
                model, max_slots=2, precision=arm, stop_text=None,
                max_new_tokens=max_new,
                int8_budget=1.0 - args.agreement,
                registry=MetricsRegistry(), session_id=f"gen-{arm}")
        except QuantGateError as e:
            failures.append(f"token-ab: int8 quant gate refused the "
                            f"head: {e.result.summary()}")
            continue
        try:
            t0 = time.perf_counter()
            streams = [eng.submit(prompt, max_new_tokens=max_new,
                                  greedy=True) for _ in range(2)]
            outs = [s.result(timeout=600.0) for s in streams]
            wall = time.perf_counter() - t0
            st = eng.stats()
            try:
                eng.assert_warm()
            except Exception as e:
                failures.append(f"token-ab: {arm} arm not warm: {e}")
            rows[arm] = {
                "tok_s": sum(len(o["ids"]) for o in outs) / wall,
                "p50_ms": st["latency_ms"]["token"].get("p50", 0.0),
                "p99_ms": st["latency_ms"]["token"].get("p99", 0.0),
                "ttft_ms": st["latency_ms"]["ttft"].get("p50", 0.0),
                "bytes_tok": head_bytes_per_token(
                    eng.spec, eng.spec.hidden_sizes[-1], arm),
                "agreement": st["head_agreement"],
                "ids": outs[0]["ids"],
            }
        finally:
            eng.shutdown()

    print(f"$/token A/B: pretrained TextGenerationLSTM, 2 concurrent "
          f"greedy streams x {max_new} tokens per arm:")
    print(f"  {'arm':5s} {'tok/s':>8s} {'p50/tok':>9s} {'p99/tok':>9s} "
          f"{'ttft':>9s} {'head B/tok':>11s} {'agree-f32':>10s}")
    for arm, r in rows.items():
        agree = ("    -" if r["agreement"] is None
                 else f"{r['agreement']:10.4f}")
        print(f"  {arm:5s} {r['tok_s']:8.1f} {r['p50_ms']:8.2f}m "
              f"{r['p99_ms']:8.2f}m {r['ttft_ms']:8.1f}m "
              f"{r['bytes_tok']:11d} {agree}")

    if {"f32", "bf16", "int8"} <= rows.keys():
        if len(rows["f32"]["ids"]) != max_new:
            failures.append(
                f"token-ab: f32 arm produced {len(rows['f32']['ids'])} "
                f"tokens, wanted {max_new}")
        if not rows["int8"]["bytes_tok"] < rows["bf16"]["bytes_tok"]:
            failures.append(
                f"token-ab: int8 head bytes/token "
                f"{rows['int8']['bytes_tok']} not strictly below bf16 "
                f"{rows['bf16']['bytes_tok']}")
        agree = rows["int8"]["agreement"]
        if agree is None or agree < args.agreement:
            failures.append(
                f"token-ab: int8 next-token agreement {agree} below "
                f"the {args.agreement:.2f} floor")
    elif "int8" not in rows:
        pass        # gate refusal already recorded
    else:
        failures.append("token-ab: missing arms "
                        f"{sorted({'f32', 'bf16', 'int8'} - rows.keys())}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: smaller soak, same gates")
    ap.add_argument("--sequences", type=int, default=None,
                    help="soak sequences (default 16 smoke / 32 full; "
                    "must exceed --max-slots to prove slot reuse)")
    ap.add_argument("--rate", type=float, default=80.0,
                    help="Poisson arrival rate, sequences/s")
    ap.add_argument("--max-slots", type=int, default=8,
                    help="engine slot budget for the soak")
    ap.add_argument("--slo-token-ms", type=float, default=None,
                    help="arm AIMD shedding over per-token p99")
    ap.add_argument("--token-p99-ms", type=float, default=250.0,
                    help="per-token p99 gate (CPU-calibrated, generous)")
    ap.add_argument("--ttft-ms", type=float, default=5000.0,
                    help="time-to-first-token p99 gate")
    ap.add_argument("--agreement", type=float, default=0.97,
                    help="int8 head next-token agreement floor vs f32")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--skip-ab", action="store_true",
                    help="skip the pretrained-artifact $/token A/B")
    args = ap.parse_args(argv)
    if args.sequences is None:
        args.sequences = 16 if args.smoke else 32

    failures = []
    run_parity(args, failures)
    run_soak(args, failures)
    if not args.skip_ab:
        run_token_ab(args, failures)
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
