"""Generative serving: continuous-batching decode soak + $/token A/B.

The claims under test (generation/engine.py):

- **correctness**: continuous batching is a pure scheduling trick — a
  sequence decoded in a shared slot batch, with other sequences joining
  and retiring around it mid-flight, must be BITWISE identical to the
  same sequence decoded alone through the model's own ``rnn_time_step``
  reference path (greedy), and a seeded sampling run must reproduce
  exactly. The masked-neutral tick makes co-residents invisible; this
  bench proves it end to end, through the HTTP streaming surface.
- **compile discipline**: the AOT bucket ladder means a soak with
  mid-stream join/leave, slot reuse and bucket resizes performs ZERO
  live compiles after warmup (watchdog-asserted).
- **$/token**: decode is memory-bound on the dense head (re-read every
  tick), so the int8 head must move strictly fewer bytes/token than
  bf16 while agreeing with the f32 head's next-token choice within the
  quant-gate budget — measured on the committed pretrained
  TextGenerationLSTM artifact, not a toy.

Load shape: ``--sequences`` clients with Poisson staggered arrivals,
each streaming ``POST /api/generate`` (SSE) through a FleetRouter-
fronted UIServer — the exact production path ``serve --generate``
wires. More sequences than slots forces mid-flight slot reuse.

v2 serving modes, each A/B'd against the v1 baseline:

- **chunked prefill**: a long prompt ingested in jitted multi-token
  scans must land its first token strictly faster than one-tick-per-
  char prefill (>= ``--prefill-speedup`` x in the full run) while
  staying bitwise-equal — same carry, same PRNG chain.
- **speculative decode**: n-gram draft + one-dispatch batched verify
  must emit a bitwise-identical stream to plain decode (acceptance
  sampling under counter-based keys makes this exact, not approximate)
  at >= ``--spec-speedup`` x fewer device dispatches per token on the
  pretrained artifact — tokens/s in the dispatch-overhead-bound
  accelerator regime (see ``run_spec_ab`` for the CPU calibration).
- **session resume**: a session captured on node A (then drained) must
  continue on a second in-proc node B via the shared ArtifactStore
  checkpoint, bitwise-equal to the undrained decode, with zero live
  compiles on B (the restore path is part of the warmup sweep).

Usage:
    python benchmarks/generation.py            # full soak + A/B table
    python benchmarks/generation.py --smoke    # CI gate: parity, zero
        # post-warmup recompiles, token p99 + TTFT bounds, int8 head
        # within budget and strictly fewer bytes/token than bf16,
        # chunked TTFT < tick TTFT, speculative stream bitwise-equal
        # to plain, cross-node session resume with zero live compiles
"""

from __future__ import annotations

import argparse
import json
import random
import threading
import time
import urllib.error
import urllib.request

from benchmarks import ab
from deeplearning4j_tpu.generation import (GenerationEngine,
                                           head_bytes_per_token,
                                           reference_decode)
from deeplearning4j_tpu.observe.registry import MetricsRegistry

SMALL_VOCAB = 31


def small_model():
    """Tiny TextGenerationLSTM geometry: fast ticks, same 3-layer
    stacked-LSTM + dense-head structure as the committed artifact."""
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    m = TextGenerationLSTM()
    m.lstm_units = 32
    m.vocab_size = SMALL_VOCAB
    m.timesteps = 8
    return m.init()


def pretrained_model():
    """The committed artifact (checksummed resource weights) — the
    $/token A/B needs real peaked distributions, not toy babble."""
    from deeplearning4j_tpu.zoo.models import TextGenerationLSTM
    return TextGenerationLSTM().init_pretrained()


# ---- parity: join/leave invisibility + seeded reproducibility ------------


def run_parity(args, failures) -> None:
    """Greedy decode under staggered join/leave must match the
    single-sequence reference bitwise; seeded sampling must reproduce
    exactly and differ across seeds."""
    model = small_model()
    rng = random.Random(1234)
    n = 8 if args.smoke else 16
    cfgs = []
    for _ in range(n):
        prompt = [rng.randrange(SMALL_VOCAB)
                  for _ in range(rng.randrange(3, 9))]
        cfgs.append((prompt, rng.randrange(12, 40)))
    refs = [reference_decode(model, p, m) for p, m in cfgs]

    eng = GenerationEngine(model, max_slots=4,
                           registry=MetricsRegistry(),
                           session_id="gen-parity")
    try:
        streams = []
        for i, (prompt, max_new) in enumerate(cfgs):
            streams.append(eng.submit(prompt, max_new_tokens=max_new,
                                      greedy=True))
            if i >= 4:      # burst fills the slots; the rest queue and
                time.sleep(rng.random() * 0.003)    # join mid-flight
        mismatch = 0
        for i, (s, ref) in enumerate(zip(streams, refs)):
            got = s.result(timeout=120.0)["ids"]
            if got != ref:
                mismatch += 1
                failures.append(
                    f"parity: sequence {i} diverged from reference "
                    f"decode (first 8: got {got[:8]} want {ref[:8]})")
        st = eng.stats()
        if st["slots"]["max_active"] < 2:
            failures.append(
                "parity: sequences never overlapped in the slot batch "
                "— join/leave was not exercised")
        a = eng.generate(cfgs[0][0], greedy=False, seed=7,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        b = eng.generate(cfgs[0][0], greedy=False, seed=7,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        c = eng.generate(cfgs[0][0], greedy=False, seed=8,
                         temperature=0.9, top_k=12, max_new_tokens=24)
        if a["ids"] != b["ids"]:
            failures.append("parity: seed 7 did not reproduce itself")
        if a["ids"] == c["ids"]:
            failures.append("parity: seeds 7 and 8 sampled identical "
                            "sequences")
        try:
            eng.assert_warm()
        except Exception as e:
            failures.append(f"parity engine not warm: {e}")
        print(f"parity: {n - mismatch}/{n} staggered sequences bitwise-"
              f"equal to reference (max co-resident "
              f"{st['slots']['max_active']}), seeded sampling "
              f"reproducible")
    finally:
        eng.shutdown()


# ---- soak: Poisson SSE streams through the fleet front door --------------


def _stream_one(url, payload, timeout=300.0):
    """One SSE client: POST /api/generate, read data: events as they
    arrive (HTTP/1.0 stream, EOF-delimited). Returns ids + the terminal
    event + client-observed TTFT."""
    req = urllib.request.Request(
        url + "/api/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    ids, terminal, ttft_ms = [], None, None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for raw in r:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:"):
                continue
            ev = json.loads(line[5:].strip())
            if "token" in ev:
                if ttft_ms is None:
                    ttft_ms = (time.perf_counter() - t0) * 1e3
                ids.append(ev["token"])
            else:
                terminal = ev
    return {"ids": ids, "terminal": terminal, "ttft_ms": ttft_ms}


def run_soak(args, failures) -> None:
    """>= ``--sequences`` sequences, Poisson staggered arrivals, each a
    streamed ``POST /api/generate`` through FleetRouter admission.
    Gates: every stream completes, every greedy output bitwise-equal to
    the sequential reference decode, slots reused mid-flight (more
    sequences than slots, co-residency observed), zero live compiles
    after warmup, token p99 / TTFT under the CPU bounds."""
    from deeplearning4j_tpu.parallel.fleet import FleetRouter
    from deeplearning4j_tpu.ui.generation_module import GenerationModule
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage

    model = small_model()
    rng = random.Random(args.seed)
    n = args.sequences
    cfgs = []
    for _ in range(n):
        prompt = [rng.randrange(SMALL_VOCAB)
                  for _ in range(rng.randrange(4, 12))]
        cfgs.append((prompt, rng.randrange(64, 129)))
    refs = [reference_decode(model, p, m) for p, m in cfgs]

    engine = GenerationEngine(model, max_slots=args.max_slots,
                              max_new_tokens=256, session_id="gen-soak")
    fleet = FleetRouter(session_id="gen-soak")
    fleet.add_generation_pool("gen", engine,
                              slo_token_ms=args.slo_token_ms)
    server = UIServer(port=0)
    server.attach(InMemoryStatsStorage())
    server.register_module(GenerationModule(router=fleet, model="gen"))
    server.start()
    try:
        fleet.assert_warm()             # warm BEFORE traffic
        results = [None] * n
        errors = []

        def client(i, prompt, max_new):
            try:
                results[i] = _stream_one(
                    server.url, {"prompt": prompt,
                                 "max_new_tokens": max_new,
                                 "greedy": True, "stream": True})
            except urllib.error.HTTPError as e:
                e.read()
                errors.append(f"sequence {i}: HTTP {e.code}")
            except Exception as e:
                errors.append(f"sequence {i}: {e}")

        threads = []
        t_start = time.perf_counter()
        for i, (prompt, max_new) in enumerate(cfgs):
            t = threading.Thread(target=client,
                                 args=(i, prompt, max_new))
            t.start()
            threads.append(t)
            time.sleep(rng.expovariate(args.rate))  # Poisson arrivals
        for t in threads:
            t.join(timeout=600)
        wall = time.perf_counter() - t_start

        failures.extend(f"soak: {e}" for e in errors)
        mismatch = 0
        ttfts = []
        for i, (res, ref) in enumerate(zip(results, refs)):
            if res is None:
                continue
            if res["terminal"] is None or "error" in (res["terminal"]
                                                      or {}):
                failures.append(
                    f"soak: sequence {i} stream ended without a done "
                    f"event ({res['terminal']})")
            if res["ids"] != ref:
                mismatch += 1
                failures.append(
                    f"soak: sequence {i} streamed ids diverged from "
                    f"the sequential reference decode")
            if res["ttft_ms"] is not None:
                ttfts.append(res["ttft_ms"])

        st = engine.stats()
        retired = sum(st["sequences"]["retired"].values())
        tok_p99 = st["latency_ms"]["token"].get("p99", 0.0)
        ttft_p99 = st["latency_ms"]["ttft"].get("p99", 0.0)
        print(f"soak: {n} sequences Poisson {args.rate:.0f}/s over "
              f"{args.max_slots} slots in {wall:.1f}s — "
              f"{st['tokens']['generated']} tokens, max co-resident "
              f"{st['slots']['max_active']}, retired {retired}")
        print(f"  engine: token p50="
              f"{st['latency_ms']['token'].get('p50', 0.0):.2f}ms "
              f"p99={tok_p99:.2f}ms  ttft p99={ttft_p99:.1f}ms  "
              f"client ttft max="
              f"{max(ttfts) if ttfts else 0.0:.1f}ms")
        if mismatch == 0 and not errors:
            print(f"  all {n} streamed outputs bitwise-equal to "
                  "reference")

        if retired < n:
            failures.append(f"soak: only {retired}/{n} sequences "
                            "retired")
        if st["slots"]["max_active"] > args.max_slots:
            failures.append("soak: active slots exceeded the bucket")
        if st["slots"]["max_active"] < 2:
            failures.append(
                "soak: sequences never co-resided — mid-flight "
                "join/leave was not exercised")
        if n <= args.max_slots:
            failures.append(
                f"soak: {n} sequences cannot prove slot reuse over "
                f"{args.max_slots} slots — raise --sequences")
        if tok_p99 > args.token_p99_ms:
            failures.append(
                f"soak: token p99 {tok_p99:.2f}ms over the "
                f"{args.token_p99_ms:.0f}ms bound")
        if ttft_p99 > args.ttft_ms:
            failures.append(
                f"soak: TTFT p99 {ttft_p99:.1f}ms over the "
                f"{args.ttft_ms:.0f}ms bound")
        try:
            engine.assert_warm()        # zero live compiles under soak
            fleet.assert_warm()
        except Exception as e:
            failures.append(f"soak: not warm after traffic: {e}")
        with urllib.request.urlopen(server.url + "/metrics") as r:
            metrics = r.read().decode()
        if "dl4j_gen_tokens_total" not in metrics:
            failures.append("soak: dl4j_gen_* series missing from "
                            "/metrics")
    finally:
        server.stop()
        fleet.shutdown()


# ---- $/token A/B: f32 / bf16 / int8 head on the committed artifact -------


def run_token_ab(args, failures) -> None:
    """Per-precision decode arms over the pretrained artifact. The $
    proxy is head bytes/token — decode re-reads the dense head every
    tick, so its resident bytes ARE the per-token memory traffic
    quantization buys down. Gates: int8 strictly fewer bytes/token than
    bf16 at >= ``--agreement`` next-token agreement vs f32 (the
    decode-level quant gate, enforced again here), every arm warm."""
    from deeplearning4j_tpu.evaluation.quant_gate import QuantGateError

    model = pretrained_model()
    prompt = "The quick brown fox "
    max_new = 64 if args.smoke else 256
    rows = {}
    for arm in ("f32", "bf16", "int8"):
        try:
            eng = GenerationEngine(
                model, max_slots=2, precision=arm, stop_text=None,
                max_new_tokens=max_new,
                int8_budget=1.0 - args.agreement,
                registry=MetricsRegistry(), session_id=f"gen-{arm}")
        except QuantGateError as e:
            failures.append(f"token-ab: int8 quant gate refused the "
                            f"head: {e.result.summary()}")
            continue
        try:
            t0 = time.perf_counter()
            streams = [eng.submit(prompt, max_new_tokens=max_new,
                                  greedy=True) for _ in range(2)]
            outs = [s.result(timeout=600.0) for s in streams]
            wall = time.perf_counter() - t0
            st = eng.stats()
            try:
                eng.assert_warm()
            except Exception as e:
                failures.append(f"token-ab: {arm} arm not warm: {e}")
            rows[arm] = {
                "tok_s": sum(len(o["ids"]) for o in outs) / wall,
                "p50_ms": st["latency_ms"]["token"].get("p50", 0.0),
                "p99_ms": st["latency_ms"]["token"].get("p99", 0.0),
                "ttft_ms": st["latency_ms"]["ttft"].get("p50", 0.0),
                "bytes_tok": head_bytes_per_token(
                    eng.spec, eng.spec.hidden_sizes[-1], arm),
                "agreement": st["head_agreement"],
                "ids": outs[0]["ids"],
            }
        finally:
            eng.shutdown()

    print(f"$/token A/B: pretrained TextGenerationLSTM, 2 concurrent "
          f"greedy streams x {max_new} tokens per arm:")
    print(f"  {'arm':5s} {'tok/s':>8s} {'p50/tok':>9s} {'p99/tok':>9s} "
          f"{'ttft':>9s} {'head B/tok':>11s} {'agree-f32':>10s}")
    for arm, r in rows.items():
        agree = ("    -" if r["agreement"] is None
                 else f"{r['agreement']:10.4f}")
        print(f"  {arm:5s} {r['tok_s']:8.1f} {r['p50_ms']:8.2f}m "
              f"{r['p99_ms']:8.2f}m {r['ttft_ms']:8.1f}m "
              f"{r['bytes_tok']:11d} {agree}")

    if {"f32", "bf16", "int8"} <= rows.keys():
        if len(rows["f32"]["ids"]) != max_new:
            failures.append(
                f"token-ab: f32 arm produced {len(rows['f32']['ids'])} "
                f"tokens, wanted {max_new}")
        if not rows["int8"]["bytes_tok"] < rows["bf16"]["bytes_tok"]:
            failures.append(
                f"token-ab: int8 head bytes/token "
                f"{rows['int8']['bytes_tok']} not strictly below bf16 "
                f"{rows['bf16']['bytes_tok']}")
        agree = rows["int8"]["agreement"]
        if agree is None or agree < args.agreement:
            failures.append(
                f"token-ab: int8 next-token agreement {agree} below "
                f"the {args.agreement:.2f} floor")
    elif "int8" not in rows:
        pass        # gate refusal already recorded
    else:
        failures.append("token-ab: missing arms "
                        f"{sorted({'f32', 'bf16', 'int8'} - rows.keys())}")


# ---- v2 A/Bs: chunked prefill / speculative decode / session resume ------


def run_prefill_ab(args, failures) -> None:
    """TTFT A/B on a long prompt: chunked prefill (jitted multi-token
    scans over the pow2 chunk ladder) vs the v1 one-tick-per-char path.
    Both arms must produce bitwise-identical output — prefill mode is a
    dispatch-shape choice, not a numerics choice. Gates: chunked TTFT
    p50 strictly below tick (smoke), >= ``--prefill-speedup`` x in the
    full run, both arms warm."""
    model = small_model()
    rng = random.Random(args.seed + 1)
    plen = 256 if args.smoke else 512
    prompt = [rng.randrange(SMALL_VOCAB) for _ in range(plen)]
    ttft, outs = {}, {}
    engines = {}
    try:
        # both arms alive before timing: interleaved rounds
        # (benchmarks/ab.py) see the same machine load
        for mode, kw in (("tick", {}),
                         ("chunked", {"prefill_chunk": 64})):
            engines[mode] = GenerationEngine(
                model, max_slots=2, registry=MetricsRegistry(),
                session_id=f"gen-prefill-{mode}", **kw)

        def _arm(mode, eng):
            def go(_r):
                outs[mode] = eng.submit(
                    prompt, max_new_tokens=8,
                    greedy=True).result(timeout=300.0)["ids"]
                return outs[mode]
            return go

        ab.interleaved({m: _arm(m, e) for m, e in engines.items()}, 3)

        for mode, eng in engines.items():
            st = eng.stats()
            ttft[mode] = st["latency_ms"]["ttft"].get("p50", 0.0)
            if mode == "chunked" and st["prefill"]["chunks"] == 0:
                failures.append("prefill-ab: chunked engine never took "
                                "the chunked path")
            try:
                eng.assert_warm()
            except Exception as e:
                failures.append(f"prefill-ab: {mode} arm not warm: {e}")
    finally:
        for eng in engines.values():
            eng.shutdown()
    speedup = (ttft["tick"] / ttft["chunked"]
               if ttft.get("chunked") else float("inf"))
    print(f"prefill A/B: {plen}-token prompt — tick TTFT p50 "
          f"{ttft['tick']:.1f}ms, chunked {ttft['chunked']:.1f}ms "
          f"({speedup:.1f}x)")
    if outs["tick"] != outs["chunked"]:
        failures.append("prefill-ab: chunked output diverged from the "
                        "tick-prefill decode bitwise")
    if not ttft["chunked"] < ttft["tick"]:
        failures.append(
            f"prefill-ab: chunked TTFT {ttft['chunked']:.1f}ms not "
            f"below tick {ttft['tick']:.1f}ms")
    if not args.smoke and speedup < args.prefill_speedup:
        failures.append(
            f"prefill-ab: TTFT speedup {speedup:.1f}x below the "
            f"{args.prefill_speedup:.0f}x floor at {plen}-token "
            f"prompts")


def run_spec_ab(args, failures) -> None:
    """Speculative decode A/B on the pretrained artifact: n-gram draft
    + one-dispatch batched verify vs plain one-token ticks. The
    acceptance rule makes the accepted stream EXACTLY the plain decode
    — so the correctness gate is bitwise equality, not distribution
    similarity.

    The throughput claim is calibrated to the regime it targets. On an
    accelerator, decode is dispatch-overhead-bound (a step's compute is
    microseconds; the host round-trip is not), so tokens/s scales with
    tokens-per-dispatch — which is what the full run gates
    (>= ``--spec-speedup`` x fewer dispatches per token than the
    one-dispatch-per-token plain path). This CPU container is the
    opposite regime — a 200-unit 3-layer step costs ~0.3 ms of real
    compute vs ~0.1 ms of dispatch overhead, so the k-step sequential
    verify scan can never win wall-clock here — wall tokens/s is
    printed for reference, not gated."""
    model = pretrained_model()
    prompt = "The quick brown fox "
    max_new = 64 if args.smoke else 1024
    rows = {}
    for mode, kw in (("plain", {}),
                     ("spec", {"speculative": args.spec_k})):
        eng = GenerationEngine(model, max_slots=2, stop_text=None,
                               max_new_tokens=max_new,
                               registry=MetricsRegistry(),
                               session_id=f"gen-{mode}", **kw)
        try:
            t0 = time.perf_counter()
            streams = [eng.submit(prompt, max_new_tokens=max_new,
                                  greedy=True) for _ in range(2)]
            results = [s.result(timeout=600.0) for s in streams]
            wall = time.perf_counter() - t0
            st = eng.stats()
            rows[mode] = {
                "tokens": sum(len(r["ids"]) for r in results),
                "tok_s": sum(len(r["ids"]) for r in results) / wall,
                "ids": [r["ids"] for r in results],
                "spec": st.get("speculative"),
            }
            try:
                eng.assert_warm()
            except Exception as e:
                failures.append(f"spec-ab: {mode} arm not warm: {e}")
        finally:
            eng.shutdown()
    sp = rows["spec"]["spec"] or {}
    # both streams ride every dispatch (they join together and run the
    # same length), so per-slot tokens/dispatch IS the dispatch
    # reduction vs plain's one dispatch per token
    reduction = (rows["spec"]["tokens"] / (2.0 * sp["dispatches"])
                 if sp.get("dispatches") else 0.0)
    print(f"speculative A/B: pretrained artifact, 2 greedy streams x "
          f"{max_new} tokens — plain {rows['plain']['tok_s']:.1f} "
          f"tok/s, spec(k={args.spec_k}) {rows['spec']['tok_s']:.1f} "
          f"tok/s, acceptance {sp.get('acceptance', 0.0):.2f}, "
          f"dispatch reduction {reduction:.2f}x")
    if rows["spec"]["ids"] != rows["plain"]["ids"]:
        failures.append("spec-ab: speculative stream diverged from the "
                        "plain decode bitwise")
    if not sp.get("proposed"):
        failures.append("spec-ab: the draft never proposed a token — "
                        "speculation was not exercised")
    if not args.smoke and reduction < args.spec_speedup:
        failures.append(
            f"spec-ab: dispatch reduction {reduction:.2f}x below the "
            f"{args.spec_speedup:.1f}x floor on the pretrained "
            f"artifact")


def run_session_resume(args, failures) -> None:
    """Cross-node session resume: node A decodes turn 1 under a session
    token and drains (shutdown); node B — a second in-proc engine
    sharing only the ArtifactStore directory — continues turn 2 from
    the store checkpoint. Gates: both turns concatenate bitwise to the
    undrained reference decode, node B's hit came from the store tier,
    and node B performs zero live compiles (slot restore is part of the
    warmup sweep)."""
    import tempfile

    from deeplearning4j_tpu.generation import (SessionStore,
                                               extract_decode_spec)
    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore

    model = small_model()
    rng = random.Random(args.seed + 2)
    prompt = [rng.randrange(SMALL_VOCAB) for _ in range(12)]
    turn = 24
    full = reference_decode(model, prompt, 2 * turn)
    spec = extract_decode_spec(model)
    with tempfile.TemporaryDirectory() as tmp:
        shared = ArtifactStore(tmp)
        eng_a = GenerationEngine(
            model, max_slots=2, registry=MetricsRegistry(),
            session_id="gen-resume-a",
            session_store=SessionStore(
                spec, store=shared, registry=MetricsRegistry(),
                session_id="gen-resume-a"))
        try:
            turn1 = eng_a.submit(prompt, max_new_tokens=turn,
                                 session="bench").result(timeout=120.0)
        finally:
            eng_a.shutdown()    # node A drains; the carry checkpoint
                                # survives in the shared store
        reg_b = MetricsRegistry()
        store_b = SessionStore(spec, store=shared, registry=reg_b,
                               session_id="gen-resume-b")
        eng_b = GenerationEngine(model, max_slots=2, registry=reg_b,
                                 session_id="gen-resume-b",
                                 session_store=store_b)
        try:
            turn2 = eng_b.submit([], max_new_tokens=turn,
                                 session="bench").result(timeout=120.0)
            if turn1["ids"] != full[:turn]:
                failures.append("session-resume: turn 1 diverged from "
                                "the reference decode")
            if turn2["ids"] != full[turn:]:
                failures.append(
                    "session-resume: node B's continuation diverged "
                    "from the undrained reference decode "
                    f"(first 8: got {turn2['ids'][:8]} want "
                    f"{full[turn:turn + 8]})")
            hits = store_b.stats()["hits"]
            if hits.get("store", 0) < 1:
                failures.append("session-resume: node B never hit the "
                                "shared store checkpoint")
            try:
                eng_b.assert_warm()
            except Exception as e:
                failures.append(f"session-resume: node B not warm "
                                f"after cross-node resume: {e}")
        finally:
            eng_b.shutdown()
    print(f"session resume: {turn}+{turn} tokens across two nodes via "
          f"the shared store — continuation bitwise-equal, node B "
          f"store hits {hits.get('store', 0)}, zero live compiles")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: smaller soak, same gates")
    ap.add_argument("--sequences", type=int, default=None,
                    help="soak sequences (default 16 smoke / 32 full; "
                    "must exceed --max-slots to prove slot reuse)")
    ap.add_argument("--rate", type=float, default=80.0,
                    help="Poisson arrival rate, sequences/s")
    ap.add_argument("--max-slots", type=int, default=8,
                    help="engine slot budget for the soak")
    ap.add_argument("--slo-token-ms", type=float, default=None,
                    help="arm AIMD shedding over per-token p99")
    ap.add_argument("--token-p99-ms", type=float, default=250.0,
                    help="per-token p99 gate (CPU-calibrated, generous)")
    ap.add_argument("--ttft-ms", type=float, default=5000.0,
                    help="time-to-first-token p99 gate")
    ap.add_argument("--agreement", type=float, default=0.97,
                    help="int8 head next-token agreement floor vs f32")
    ap.add_argument("--prefill-speedup", type=float, default=4.0,
                    help="chunked-vs-tick TTFT floor (full run, 512-"
                    "token prompts)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculative draft length for the spec A/B")
    ap.add_argument("--spec-speedup", type=float, default=2.0,
                    help="speculative dispatch-reduction floor vs "
                    "plain decode (full run, pretrained artifact)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--skip-ab", action="store_true",
                    help="skip the pretrained-artifact $/token and "
                    "speculative A/Bs")
    args = ap.parse_args(argv)
    if args.sequences is None:
        args.sequences = 16 if args.smoke else 32

    failures = []
    run_parity(args, failures)
    run_prefill_ab(args, failures)
    run_session_resume(args, failures)
    run_soak(args, failures)
    if not args.skip_ab:
        run_token_ab(args, failures)
        run_spec_ab(args, failures)
    for f in failures:
        print(f"FAIL: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
