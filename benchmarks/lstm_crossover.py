"""Fused-Pallas-LSTM vs XLA-scan crossover sweep.

Measures forward+backward wall time of the two recurrence
implementations over a (batch, hidden, T) grid and prints the winner
per geometry — the measurement source for
``ops/pallas_lstm._MEASURED_FUSED_WINS`` (the dispatch table routes to
the fused kernel ONLY where this bench shows it winning; the attention
crossover discipline from round 5).

Methodology matches benchmarks/attn_crossover.py: K iterations chained
inside one jitted dispatch (the per-dispatch tunnel overhead — tens of
ms through the tunneled PJRT transport — would otherwise swamp
per-tick effects), gradients taken through a sum loss, best of R
repetitions, host read as the only true sync.

Run on hardware:
    python benchmarks/lstm_crossover.py                  # default grid
    python benchmarks/lstm_crossover.py --quick          # BASELINE geometry only
    python benchmarks/lstm_crossover.py --block-t 1 4 8  # sweep tick blocking
"""

import argparse
import functools
import time

import numpy as np


def bench(step, args, k=10, reps=3):
    """Median-free best-of-reps timing of ``k`` chained calls inside one
    jit. Returns seconds per call."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def many(args):
        def body(carry, _):
            out = step(*carry)
            # chain: mix each output back into the inputs so XLA cannot
            # hoist or dedupe iterations
            new_args = tuple(a + 0.0 * jnp.sum(o) for a, o in
                             zip(carry, out)) if isinstance(out, tuple) \
                else tuple(a + 0.0 * jnp.sum(out) for a in carry)
            return new_args, ()
        out, _ = jax.lax.scan(body, args, None, length=k)
        return out

    r = many(args)  # compile + warm
    np.asarray(jax.tree_util.tree_leaves(r)[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        r = many(args)
        np.asarray(jax.tree_util.tree_leaves(r)[0])
        best = min(best, (time.perf_counter() - t0) / k)
    return best


def make_steps(batch, hidden, seq, dtype, block_t):
    """Returns (scan_step, fused_step): each maps (zx, h0, c0, wh) ->
    grads of a sum loss through the full recurrence (fwd+bwd)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import pallas_lstm

    def scan_fwd(zx, h0, c0, wh):
        h = hidden

        def cell(carry, zx_t):
            h_prev, c_prev = carry
            z = zx_t + h_prev @ wh
            i = jax.nn.sigmoid(z[:, :h])
            f = jax.nn.sigmoid(z[:, h:2 * h])
            o = jax.nn.sigmoid(z[:, 2 * h:3 * h])
            g = jnp.tanh(z[:, 3 * h:])
            c = f * c_prev + i * g
            hy = o * jnp.tanh(c)
            return (hy, c), hy

        (hT, cT), ys = jax.lax.scan(cell, (h0, c0), zx)
        return ys, hT, cT

    def fused_fwd(zx, h0, c0, wh):
        return pallas_lstm.lstm_fused(zx, h0, c0, wh, None,
                                      block_t=block_t, interpret=False)

    def grad_step(fwd):
        def loss(zx, h0, c0, wh):
            ys, hT, cT = fwd(zx, h0, c0, wh)
            return (jnp.sum(ys.astype(jnp.float32) ** 2)
                    + jnp.sum(hT.astype(jnp.float32))
                    + jnp.sum(cT.astype(jnp.float32)))
        return jax.grad(loss, argnums=(0, 1, 2, 3))
    return grad_step(scan_fwd), grad_step(fused_fwd)


def run_geometry(batch, hidden, seq, dtype, block_t, k, reps):
    import jax.numpy as jnp
    dt = {"f32": jnp.float32, "bf16": jnp.bfloat16}[dtype]
    rng = np.random.default_rng(0)
    zx = jnp.asarray(rng.normal(size=(seq, batch, 4 * hidden)) * 0.1, dt)
    h0 = jnp.zeros((batch, hidden), dt)
    c0 = jnp.zeros((batch, hidden), dt)
    wh = jnp.asarray(rng.normal(size=(hidden, 4 * hidden)) * 0.05, dt)
    scan_step, fused_step = make_steps(batch, hidden, seq, dt, block_t)
    args = (zx, h0, c0, wh)
    t_scan = bench(scan_step, args, k=k, reps=reps)
    try:
        t_fused = bench(fused_step, args, k=k, reps=reps)
    except Exception as e:  # kernel refused this geometry (e.g. VMEM)
        print(f"  fused FAILED ({type(e).__name__}) "
              f"b={batch} h={hidden} T={seq} bt={block_t}")
        return None
    tokens = batch * seq
    print(f"b={batch:5d} h={hidden:4d} T={seq:4d} {dtype} bt={block_t}: "
          f"scan {t_scan*1e3:8.3f} ms ({tokens/t_scan/1e6:7.2f} Mtok/s)  "
          f"fused {t_fused*1e3:8.3f} ms ({tokens/t_fused/1e6:7.2f} Mtok/s)  "
          f"speedup {t_scan/t_fused:5.2f}x  "
          f"winner={'FUSED' if t_fused < t_scan else 'scan'}")
    return (batch, hidden, seq, block_t, t_scan, t_fused)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="BASELINE TextGenerationLSTM geometry only")
    ap.add_argument("--dtype", default="bf16", choices=["f32", "bf16"])
    ap.add_argument("--block-t", type=int, nargs="+", default=[1])
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax
    backend = jax.default_backend()
    print(f"backend={backend} dtype={args.dtype}")
    if backend != "tpu":
        print("WARNING: not a TPU — fused kernel would run in interpret "
              "mode; timings below are meaningless for dispatch tables.")

    if args.quick:
        grid = [(256, 512, 128)]
    else:
        grid = [(b, h, t)
                for b in (64, 256, 1024)
                for h in (256, 512, 1024)
                for t in (32, 128, 512)]
        # decode-shape geometries: the generation/ engine's tick is a
        # T=1 step over a small slot batch (continuous batching keeps
        # batch at the slot-bucket sizes). Swept here so the dispatch
        # table has the decode consumer's shapes ready the first time a
        # chip session runs this — a fused win at T=1 would move the
        # serving tick, not just training.
        grid += [(b, h, 1)
                 for b in (1, 8, 16)
                 for h in (256, 512)]

    wins = []
    for (b, h, t) in grid:
        for bt in args.block_t:
            r = run_geometry(b, h, t, args.dtype, bt, args.k, args.reps)
            if r is not None and r[5] < r[4]:
                wins.append(r)
    if wins:
        print("\nfused wins at (batch, hidden, seq, block_t):")
        for b, h, t, bt, ts, tf in wins:
            print(f"  ({b}, {h}, {t})  bt={bt}  {ts/tf:.2f}x")
        print("-> encode as rules in ops/pallas_lstm._MEASURED_FUSED_WINS")
    else:
        print("\nfused never won: keep _MEASURED_FUSED_WINS empty "
              "(auto-dispatch stays on scan) and record the post-mortem "
              "in PERF_ANALYSIS.md")


if __name__ == "__main__":
    main()
