"""Autotune sweep engine: measure every registered tunable, persist one
TunedConfig artifact every node loads at start.

Each tunable's candidate grid runs as an interleaved A/B (benchmarks/
ab.py: alternating arms, warmup-round exclusion, median headline) with
the recompile watchdog asserted per cell — a cell that paid a live
compile measured the compiler, not the knob. The winners (ties prefer
the committed hand-tuned default) are written through
``deeplearning4j_tpu.optimize.autotune.save_tuned`` into the shared
ArtifactStore: blob + manifest-atomic-LAST, fingerprinted by backend /
jax / jaxlib / registry version / model weights sha256, so a second
node (or a fresh process) starts serving from the measurements with
zero live compiles — and a different machine falls through to the
committed defaults instead of inheriting this one's constants.

Two constraint-shaped tunables:

- ``retrieval.nprobe`` sweeps against the recall@10 >= 0.95 gate as a
  hard CONSTRAINT — a shallow probe that misses spilled fringe rows
  (the measured 0.941@32 case on the 1M index) can never win, however
  fast it is.
- ``ops.lstm_dispatch`` only measures on a TPU backend. On CPU the
  tuner records an explicit scan-fallback DECISION (the table stays
  empty on purpose, with the reason persisted) instead of leaving it
  silently unpopulated.

Usage:
    python -m benchmarks.autotune                  # full sweep
    python -m benchmarks.autotune --smoke          # CI gate: tiny
        # candidate subset; asserts artifact written, reloaded,
        # consumed (engine geometry + bitwise outputs), tuned >=
        # hand-tuned default on the serving tunable, and a fresh
        # subprocess serving from the artifact with zero live compiles
    python -m benchmarks.autotune --verify-node --store DIR
        # (internal) the fresh-process consumer the smoke spawns
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from benchmarks import ab

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AOT_KEY = "autotune-model-aot"   # store key for the consumer AOT table


def _counters():
    from deeplearning4j_tpu.observe.registry import default_registry
    reg = default_registry()
    runs = reg.counter("dl4j_autotune_runs_total",
                       "completed autotune sweep runs (one persisted "
                       "TunedConfig artifact each)")
    cells = reg.counter("dl4j_autotune_cells_total",
                        "measured sweep cells (one candidate x one "
                        "tunable, all interleaved rounds), per tunable")
    return runs, cells


# ---- serving.batch_limit -------------------------------------------------

def sweep_serving_batch_limit(model, candidates, *, rounds, clients,
                              requests, cells) -> dict:
    """Interleaved closed-loop throughput per batch_limit candidate.
    Every candidate engine stays alive for the whole sweep so the
    rotation hits warm arms only; each cell ends watchdog-asserted."""
    from benchmarks.serving import closed_loop, make_engine
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    engines = {c: make_engine(model, pipelined=True,
                              session=f"tune-bl{c}", batch_limit=c)
               for c in candidates}
    try:
        arms = {}
        for c, eng in engines.items():
            def run(r, eng=eng):
                t, _ = closed_loop(eng, clients, requests, 2, seed=r)
                return t
            arms[str(c)] = run
        samples = ab.interleaved(arms, rounds, warmup=1)
        for eng in engines.values():
            eng.assert_warm()           # a compiling cell is not a cell
        med = ab.median_of(samples)
        measured = [(c, med[str(c)]) for c in candidates]
        for c, s in measured:
            cells.inc(1.0, tunable="serving.batch_limit")
            print(f"  serving.batch_limit={c:<4d} {s:9.1f} req/s")
        return choose(REGISTRY["serving.batch_limit"], measured)
    finally:
        for eng in engines.values():
            eng.shutdown()


# ---- retrieval.nprobe (recall floor is a constraint) ---------------------

def sweep_retrieval_nprobe(candidates, *, rounds, seed, cells,
                           n=4096, dim=16, k_blobs=96, clusters=16,
                           recall_floor=0.95) -> dict:
    """qps per nprobe candidate over a spill-prone geometry (more blobs
    than clusters, so capacity-balanced assignment spills dense-blob
    fringe rows — the measured 0.941@32 failure mode scaled down).
    Candidates under the recall floor are EXCLUDED, not merely
    penalized: recall is a constraint, not a tunable."""
    from benchmarks.neighbors import blob_corpus, exact_oracle, recall_at
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
    from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex

    k, batch = 10, 16
    corpus = blob_corpus(n, dim, k_blobs=k_blobs, seed=seed)
    rng = np.random.default_rng(seed + 1)
    probes = corpus[rng.integers(n, size=batch)] + rng.normal(
        size=(batch, dim)).astype(np.float32) * 0.05
    _, oracle = exact_oracle(corpus, probes, k)
    # one index per engine: RetrievalEngine._install takes ownership of
    # the shard arrays (drops the host copies), so candidates cannot
    # share an index object; the build is seeded, so every candidate
    # sees the identical geometry
    engines = {
        c: RetrievalEngine(
            ShardedCorpusIndex.build(corpus, shard_rows=n,
                                     precision="f32",
                                     ivf_clusters=clusters, seed=seed),
            k_ladder=(k,), max_batch=batch, nprobe=c,
            session_id=f"tune-np{c}")
        for c in candidates}
    try:
        for eng in engines.values():
            eng.warmup()
        arms = {}
        for c, eng in engines.items():
            def run(r, eng=eng):
                t0 = time.perf_counter()
                eng.search(probes, k, mode="ivf")
                return batch / (time.perf_counter() - t0)
            arms[str(c)] = run
        samples = ab.interleaved(arms, rounds, warmup=1)
        med = ab.median_of(samples)
        measured, excluded, recalls = [], {}, {}
        for c, eng in engines.items():
            if eng.recompiles_after_warmup:
                raise AssertionError(
                    f"nprobe={c} cell paid {eng.recompiles_after_warmup}"
                    " live compile(s)")
            _, ids = eng.search(probes, k, mode="ivf")
            rec = recall_at(np.asarray(ids), oracle)
            recalls[c] = rec
            measured.append((c, med[str(c)]))
            cells.inc(1.0, tunable="retrieval.nprobe")
            mark = ""
            if rec < recall_floor:
                excluded[c] = (f"recall@{k} {rec:.3f} below the "
                               f"{recall_floor} floor")
                mark = "  EXCLUDED (recall floor)"
            print(f"  retrieval.nprobe={c:<4d} {med[str(c)]:9.1f} qps"
                  f"  recall@{k}={rec:.3f}{mark}")
        d = choose(REGISTRY["retrieval.nprobe"], measured,
                   excluded=excluded,
                   note=f"fastest candidate holding recall@{k} >= "
                        f"{recall_floor} on a {k_blobs}-blob/"
                        f"{clusters}-cluster spill geometry")
        d["recalls"] = {str(c): r for c, r in recalls.items()}
        return d
    finally:
        for eng in engines.values():
            eng.shutdown()


# ---- ops.lstm_dispatch (fill-or-retire the empty table) ------------------

def sweep_lstm_dispatch(*, rounds, cells) -> dict:
    """On a TPU backend: time the fused Pallas kernel vs the XLA scan
    per geometry and persist winning geometries as dispatch rules. On
    anything else: record an explicit scan-fallback decision — the
    committed table stays empty, but now the artifact says WHY."""
    import jax
    from deeplearning4j_tpu.optimize.autotune import REGISTRY
    backend = jax.default_backend()
    t = REGISTRY["ops.lstm_dispatch"]
    if backend != "tpu":
        cells.inc(1.0, tunable="ops.lstm_dispatch")
        reason = (f"backend={backend}: the fused Pallas kernel only "
                  "dispatches on TPU, so the crossover cannot be "
                  "measured here — explicit scan fallback, table "
                  "stays empty until a chip-attached tuning run")
        print(f"  ops.lstm_dispatch: {reason}")
        return {"tunable": t.name, "value": [], "default": list(t.default),
                "unit": t.unit, "higher_is_better": t.higher_is_better,
                "score": None, "measured": [], "excluded": [],
                "impl": "scan", "reason": reason}

    # chip-attached path: fused-vs-scan wall time per geometry; a
    # geometry where fused wins becomes a (min_batch,min_hidden,min_seq)
    # rule. Never exercised in the CPU CI — the CPU branch above is.
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.pallas_lstm import lstm_fused

    def scan_ref(zx, h0, c0, wh):
        nh = h0.shape[-1]

        def step(carry, z_t):
            h, c = carry
            z = z_t + jnp.dot(h, wh)
            i = jax.nn.sigmoid(z[:, :nh])
            f = jax.nn.sigmoid(z[:, nh:2 * nh])
            o = jax.nn.sigmoid(z[:, 2 * nh:3 * nh])
            g = jnp.tanh(z[:, 3 * nh:])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h
        (_, _), ys = jax.lax.scan(step, (h0, c0), zx)
        return ys

    rng = np.random.default_rng(0)
    wins, measured = [], []
    for (b, h, s) in ((8, 64, 32), (32, 128, 64), (64, 256, 128)):
        zx = jnp.asarray(rng.normal(size=(s, b, 4 * h)), jnp.float32)
        h0 = jnp.zeros((b, h), jnp.float32)
        c0 = jnp.zeros((b, h), jnp.float32)
        wh = jnp.asarray(rng.normal(size=(h, 4 * h)) * 0.1, jnp.float32)
        fused = jax.jit(lambda zx, h0, c0, wh: lstm_fused(
            zx, h0, c0, wh, interpret=False))
        scan = jax.jit(scan_ref)
        for fn in (fused, scan):
            jax.block_until_ready(fn(zx, h0, c0, wh))  # compile outside

        def timed(fn):
            def run(r):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(zx, h0, c0, wh))
                return time.perf_counter() - t0
            return run
        med = ab.median_of(ab.interleaved(
            {"fused": timed(fused), "scan": timed(scan)},
            rounds, warmup=1))
        cells.inc(1.0, tunable="ops.lstm_dispatch")
        measured.append([[b, h, s],
                         {"fused_s": med["fused"], "scan_s": med["scan"]}])
        print(f"  ops.lstm_dispatch ({b},{h},{s}): fused "
              f"{med['fused'] * 1e3:.2f}ms vs scan "
              f"{med['scan'] * 1e3:.2f}ms")
        if med["fused"] < med["scan"]:
            wins.append([b, h, s])
    return {"tunable": t.name, "value": wins, "default": list(t.default),
            "unit": t.unit, "higher_is_better": t.higher_is_better,
            "score": None, "measured": measured, "excluded": [],
            "impl": "fused" if wins else "scan",
            "reason": f"measured fused-vs-scan crossover on {backend}"}


# ---- full-run-only sweeps ------------------------------------------------

def sweep_fit_k_steps(candidates, *, rounds, cells) -> dict:
    """Steps/s per K (scanned multi-step dispatch), one model per arm
    (fit mutates params), whole epochs interleaved."""
    from benchmarks.input_pipeline import (SleepyIterator, build_model,
                                           make_batches)
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    batches = make_batches(24, batch=256)
    models = {c: build_model(width=256) for c in candidates}
    for c, m in models.items():       # compile outside the timed region
        m.fit(SleepyIterator(batches[:max(2, c)], 0.0), epochs=1,
              k_steps=c)
    arms = {}
    for c, m in models.items():
        def run(r, m=m, c=c):
            t0 = time.perf_counter()
            m.fit(SleepyIterator(batches, 0.0), epochs=1, k_steps=c)
            return len(batches) / (time.perf_counter() - t0)
        arms[str(c)] = run
    med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
    measured = [(c, med[str(c)]) for c in candidates]
    for c, s in measured:
        cells.inc(1.0, tunable="fit.k_steps")
        print(f"  fit.k_steps={c:<4d} {s:9.1f} steps/s")
    return choose(REGISTRY["fit.k_steps"], measured)


def sweep_fit_batch(candidates, *, rounds, cells) -> dict:
    """Examples/s per batch size at a fixed example budget."""
    from benchmarks.input_pipeline import (SleepyIterator, build_model,
                                           make_batches)
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    budget = 6144                       # examples per epoch, every arm
    data = {c: make_batches(max(1, budget // c), batch=c)
            for c in candidates}
    models = {c: build_model(width=256) for c in candidates}
    for c, m in models.items():
        m.fit(SleepyIterator(data[c][:2], 0.0), epochs=1)
    arms = {}
    for c, m in models.items():
        def run(r, m=m, c=c):
            t0 = time.perf_counter()
            m.fit(SleepyIterator(data[c], 0.0), epochs=1)
            return len(data[c]) * c / (time.perf_counter() - t0)
        arms[str(c)] = run
    med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
    measured = [(c, med[str(c)]) for c in candidates]
    for c, s in measured:
        cells.inc(1.0, tunable="fit.batch")
        print(f"  fit.batch={c:<6d} {s:9.0f} examples/s")
    return choose(REGISTRY["fit.batch"], measured)


def sweep_feeder_depth(candidates, *, rounds, cells) -> dict:
    """Steps/s per prefetch depth with a simulated host-ETL cost the
    double buffer is meant to hide."""
    from benchmarks.input_pipeline import (SleepyIterator, build_model,
                                           make_batches)
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    batches = make_batches(16, batch=256)
    models = {c: build_model(width=256) for c in candidates}
    for c, m in models.items():
        m.fit(SleepyIterator(batches[:2], 0.0), epochs=1, prefetch=c)
    arms = {}
    for c, m in models.items():
        def run(r, m=m, c=c):
            t0 = time.perf_counter()
            m.fit(SleepyIterator(batches, 0.004), epochs=1, prefetch=c)
            return len(batches) / (time.perf_counter() - t0)
        arms[str(c)] = run
    med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
    measured = [(c, med[str(c)]) for c in candidates]
    for c, s in measured:
        cells.inc(1.0, tunable="feeder.depth")
        print(f"  feeder.depth={c:<4d} {s:9.1f} steps/s")
    return choose(REGISTRY["feeder.depth"], measured)


def sweep_generation_slots(candidates, *, rounds, cells) -> dict:
    """Aggregate tok/s per slot-count candidate: each round submits
    ``slots`` concurrent greedy streams and times the drain."""
    from benchmarks.generation import SMALL_VOCAB, small_model
    from deeplearning4j_tpu.generation import GenerationEngine
    from deeplearning4j_tpu.observe.registry import MetricsRegistry
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    import random as _random
    model = small_model()
    rng = _random.Random(11)
    prompt = [rng.randrange(SMALL_VOCAB) for _ in range(16)]
    max_new = 24
    engines = {c: GenerationEngine(model, max_slots=c, stop_text=None,
                                   registry=MetricsRegistry(),
                                   session_id=f"tune-slots{c}")
               for c in candidates}
    try:
        arms = {}
        for c, eng in engines.items():
            def run(r, eng=eng, c=c):
                t0 = time.perf_counter()
                streams = [eng.submit(prompt, max_new_tokens=max_new,
                                      greedy=True) for _ in range(c)]
                n = sum(len(s.result(timeout=600.0)["ids"])
                        for s in streams)
                return n / (time.perf_counter() - t0)
            arms[str(c)] = run
        med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
        for eng in engines.values():
            eng.assert_warm()
        measured = [(c, med[str(c)]) for c in candidates]
        for c, s in measured:
            cells.inc(1.0, tunable="generation.max_slots")
            print(f"  generation.max_slots={c:<4d} {s:9.1f} tok/s")
        return choose(REGISTRY["generation.max_slots"], measured)
    finally:
        for eng in engines.values():
            eng.shutdown()


def sweep_prefill_chunk(candidates, *, rounds, cells) -> dict:
    """TTFT (ms, lower is better) per prefill-chunk candidate on a
    long prompt — 0 is the one-tick-per-token baseline."""
    from benchmarks.generation import SMALL_VOCAB, small_model
    from deeplearning4j_tpu.generation import GenerationEngine
    from deeplearning4j_tpu.observe.registry import MetricsRegistry
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    import random as _random
    model = small_model()
    rng = _random.Random(12)
    prompt = [rng.randrange(SMALL_VOCAB) for _ in range(256)]
    engines = {c: GenerationEngine(model, max_slots=2, stop_text=None,
                                   prefill_chunk=c,
                                   registry=MetricsRegistry(),
                                   session_id=f"tune-chunk{c}")
               for c in candidates}
    try:
        arms = {}
        for c, eng in engines.items():
            def run(r, eng=eng):
                t0 = time.perf_counter()
                s = eng.submit(prompt, max_new_tokens=1, greedy=True)
                next(iter(s))           # first token = TTFT
                s.result(timeout=300.0)
                return (time.perf_counter() - t0) * 1e3
            arms[str(c)] = run
        med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
        for eng in engines.values():
            eng.assert_warm()
        measured = [(c, med[str(c)]) for c in candidates]
        for c, s in measured:
            cells.inc(1.0, tunable="generation.prefill_chunk")
            print(f"  generation.prefill_chunk={c:<4d} {s:9.1f} ms TTFT")
        return choose(REGISTRY["generation.prefill_chunk"], measured)
    finally:
        for eng in engines.values():
            eng.shutdown()


def sweep_retrieval_k_ladder(candidates, *, rounds, seed, cells) -> dict:
    """qps at k=10 per warmed-ladder candidate (a shorter ladder warms
    fewer executables; a longer one pads less at odd k)."""
    from benchmarks.neighbors import blob_corpus
    from deeplearning4j_tpu.optimize.autotune import REGISTRY, choose
    from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
    from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex
    n, dim, batch = 4096, 16, 16
    corpus = blob_corpus(n, dim, k_blobs=16, seed=seed)
    rng = np.random.default_rng(seed + 1)
    probes = corpus[rng.integers(n, size=batch)]
    # one seeded-identical index per engine: engines take ownership of
    # the shard arrays at install
    engines = {tuple(c): RetrievalEngine(
        ShardedCorpusIndex.build(corpus, shard_rows=n,
                                 precision="f32", seed=seed),
        k_ladder=tuple(c), max_batch=batch,
        session_id=f"tune-kl{'-'.join(str(k) for k in c)}")
        for c in candidates}
    try:
        for eng in engines.values():
            eng.warmup()
        arms = {}
        for c, eng in engines.items():
            def run(r, eng=eng):
                t0 = time.perf_counter()
                eng.search(probes, 10, mode="brute")
                return batch / (time.perf_counter() - t0)
            arms[str(c)] = run
        med = ab.median_of(ab.interleaved(arms, rounds, warmup=1))
        measured = [(list(c), med[str(c)]) for c in engines]
        for c, s in measured:
            cells.inc(1.0, tunable="retrieval.k_ladder")
            print(f"  retrieval.k_ladder={c!r:<14} {s:9.1f} qps")
        return choose(REGISTRY["retrieval.k_ladder"], measured)
    finally:
        for eng in engines.values():
            eng.shutdown()


# ---- the run: sweep -> persist -> reload -> consume ----------------------

def _model_and_fingerprint(width):
    from benchmarks.serving import build_model
    from deeplearning4j_tpu.optimize.autotune import fingerprint
    model = build_model(width=width)     # seeded: any node rebuilds the
    fp = fingerprint(model.train_state.params,   # same weights digest
                     model_version="bench")
    return model, fp


def run_sweep(args, smoke: bool) -> int:
    from deeplearning4j_tpu.optimize import autotune
    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore

    store_dir = args.store
    if store_dir is None:
        import tempfile
        store_dir = tempfile.mkdtemp(prefix="dl4j-autotune-")
    store = ArtifactStore(store_dir)
    runs, cells = _counters()
    rounds = 3 if smoke else args.rounds
    width = 64 if smoke else args.width

    model, fp = _model_and_fingerprint(width)
    cfg = autotune.TunedConfig(fingerprint=fp, source="measured")

    label = "smoke" if smoke else "full"
    print(f"autotune {label}: sweeping into {store_dir}")
    print("serving.batch_limit (interleaved closed-loop):")
    cfg.record(sweep_serving_batch_limit(
        model, (8, 16, 32) if smoke else (8, 16, 32, 64),
        rounds=rounds, clients=4, requests=8 if smoke else 25,
        cells=cells))
    print("retrieval.nprobe (recall floor as constraint):")
    cfg.record(sweep_retrieval_nprobe(
        (1, 4, 16) if smoke else (1, 2, 4, 8, 16),
        rounds=rounds, seed=args.seed, cells=cells))
    print("ops.lstm_dispatch (fill-or-retire):")
    cfg.record(sweep_lstm_dispatch(rounds=rounds, cells=cells))
    if not smoke:
        print("fit.k_steps (scanned multi-step dispatch):")
        cfg.record(sweep_fit_k_steps((1, 2, 4, 8), rounds=rounds,
                                     cells=cells))
        print("fit.batch (fixed example budget):")
        cfg.record(sweep_fit_batch((128, 256, 384), rounds=rounds,
                                   cells=cells))
        print("feeder.depth (ETL-hiding double buffer):")
        cfg.record(sweep_feeder_depth((1, 2, 4), rounds=rounds,
                                      cells=cells))
        print("generation.max_slots (continuous batching):")
        cfg.record(sweep_generation_slots((2, 4, 8), rounds=rounds,
                                          cells=cells))
        print("generation.prefill_chunk (TTFT, lower wins):")
        cfg.record(sweep_prefill_chunk((0, 16, 64), rounds=rounds,
                                       cells=cells))
        print("retrieval.k_ladder:")
        cfg.record(sweep_retrieval_k_ladder(
            ((1, 10, 100), (10, 100)), rounds=rounds, seed=args.seed,
            cells=cells))

    path = autotune.save_tuned(store, cfg)
    runs.inc(1.0)
    print(f"persisted TunedConfig -> {path}")
    for name, tuned, default, reason in cfg.summary_rows():
        same = tuned == default or (
            isinstance(tuned, (list, tuple))
            and isinstance(default, (list, tuple))
            and list(tuned) == list(default))
        marker = " (= default)" if same else ""
        print(f"  {name:<26} {tuned!r:<14} default={default!r}"
              f"{marker}")

    failures = []

    # gate 1: a fresh in-process load round-trips bit-for-bit
    cfg2 = autotune.load_tuned(store, expect=fp)
    if cfg2.load_outcome != "loaded":
        failures.append(f"reload outcome {cfg2.load_outcome!r} "
                        f"({cfg2.load_reason})")
    elif json.dumps(cfg2.values, sort_keys=True) != json.dumps(
            json.loads(json.dumps(cfg.values)), sort_keys=True):
        failures.append("reloaded values diverge from the sweep's")

    # gate 2: tuned >= the hand-tuned default on the serving tunable
    d = cfg.decisions["serving.batch_limit"]
    by_cand = {c: s for c, s in d["measured"]}
    if d["score"] < by_cand[d["default"]]:
        failures.append(
            f"winner batch_limit={d['value']} at {d['score']:.1f} "
            f"req/s under the default's {by_cand[d['default']]:.1f}")
    print(f"tuned-vs-default: batch_limit={d['value']} "
          f"{d['score']:.1f} req/s vs default={d['default']} "
          f"{by_cand[d['default']]:.1f} req/s")

    # gate 3: the nprobe constraint actually bit — and never won
    dn = cfg.decisions["retrieval.nprobe"]
    if smoke and not dn["excluded"]:
        failures.append("nprobe sweep: no candidate fell below the "
                        "recall floor — the spill fixture lost its "
                        "spill (geometry drifted?)")
    banned = {json.dumps(c) for c, _ in dn["excluded"]}
    if json.dumps(dn["value"]) in banned:
        failures.append(f"nprobe winner {dn['value']} violates the "
                        "recall floor")

    # gate 4: a consumer engine sizes itself from the artifact, serves
    # bitwise-unchanged outputs, and publishes its AOT table for node B
    from deeplearning4j_tpu.observe.registry import MetricsRegistry
    from deeplearning4j_tpu.parallel.serving import ServingEngine
    eng = ServingEngine(model, batch_limit=None, tuned_config=cfg2,
                        feature_shape=(128,), registry=MetricsRegistry(),
                        session_id="tune-consumer",
                        aot_cache_dir=store.cache_dir(AOT_KEY),
                        model_version="bench")
    try:
        if eng.batch_limit != cfg2.get("serving.batch_limit"):
            failures.append(
                f"consumer engine batch_limit={eng.batch_limit}, tuned "
                f"artifact says {cfg2.get('serving.batch_limit')}")
        rng = np.random.default_rng(args.seed)
        x = rng.normal(size=(5, 128)).astype(np.float32)
        want = np.asarray(model.output(x))
        got = np.asarray(eng.output(x))
        if want.tobytes() != got.tobytes():
            failures.append("tuned engine output not bitwise-equal to "
                            "direct model.output")
        digest = __import__("hashlib").sha256(want.tobytes()).hexdigest()
        eng.assert_warm()
    finally:
        eng.shutdown()

    # gate 5: node B — a fresh process serves from node A's artifact
    # with zero live compiles and bitwise-identical answers
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-m", "benchmarks.autotune", "--verify-node",
         "--store", store_dir, "--width", str(width),
         "--seed", str(args.seed)],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        failures.append(f"verify-node exited {out.returncode}:\n"
                        f"{out.stdout[-2000:]}\n{out.stderr[-2000:]}")
    else:
        report = json.loads(out.stdout.strip().splitlines()[-1])
        if report["outcome"] != "loaded":
            failures.append(f"node B load outcome {report['outcome']!r}")
        if report["batch_limit"] != cfg2.get("serving.batch_limit"):
            failures.append(f"node B batch_limit={report['batch_limit']}")
        if report["recompiles"] != 0:
            failures.append(f"node B paid {report['recompiles']} live "
                            "compile(s)")
        if report["aot_hits"] < 1:
            failures.append("node B compiled its ladder instead of "
                            "loading node A's AOT table")
        if report["digest"] != digest:
            failures.append("node B outputs diverge bitwise from "
                            "node A")
        print(f"node B: loaded artifact, batch_limit="
              f"{report['batch_limit']}, {report['aot_hits']} AOT "
              f"hits, 0 live compiles, outputs bitwise-identical")

    if failures:
        print(f"autotune {label}: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"autotune {label}: PASS — artifact persisted, reloaded, "
          "consumed across processes with zero live compiles; tuned "
          ">= hand-tuned default; recall floor enforced")
    return 0


# ---- node B (spawned by the smoke, or run by hand on a second node) ------

def run_verify_node(args) -> int:
    """Fresh-process consumer: load the tuned artifact from the shared
    store, rebuild the (seeded) bench model, and serve from both the
    tuned geometry and node A's published AOT table. Emits one JSON
    line the parent asserts on."""
    import hashlib

    from deeplearning4j_tpu.observe.registry import MetricsRegistry
    from deeplearning4j_tpu.optimize import autotune
    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
    from deeplearning4j_tpu.parallel.serving import ServingEngine

    store = ArtifactStore(args.store)
    model, fp = _model_and_fingerprint(64 if args.width is None
                                       else args.width)
    cfg = autotune.load_tuned(store, expect=fp)
    if cfg.load_outcome != "loaded":
        print(json.dumps({"outcome": cfg.load_outcome,
                          "reason": cfg.load_reason}))
        return 1
    eng = ServingEngine(model, batch_limit=None, tuned_config=cfg,
                        feature_shape=(128,), registry=MetricsRegistry(),
                        session_id="tune-consumer",
                        aot_cache_dir=store.cache_dir(AOT_KEY),
                        model_version="bench")
    try:
        rng = np.random.default_rng(args.seed)
        x = rng.normal(size=(5, 128)).astype(np.float32)
        out = np.asarray(eng.output(x))
        for size in (1, 3, eng.batch_limit):
            eng.output(rng.normal(size=(size, 128)).astype(np.float32))
        eng.assert_warm()
        print(json.dumps({
            "outcome": "loaded",
            "batch_limit": eng.batch_limit,
            "recompiles": eng.recompiles_after_warmup,
            "aot_hits": eng.aot_cache.hits,
            "digest": hashlib.sha256(out.tobytes()).hexdigest(),
        }))
        return 0
    finally:
        eng.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: tiny candidate subset + the full "
                    "persist/reload/consume/two-process assertion chain")
    ap.add_argument("--verify-node", action="store_true",
                    help="(internal) fresh-process consumer mode")
    ap.add_argument("--store", default=None, metavar="DIR",
                    help="ArtifactStore root to persist into (default: "
                    "a fresh temp dir)")
    ap.add_argument("--rounds", type=int, default=5,
                    help="interleaved rounds per sweep (after 1 warmup)")
    ap.add_argument("--width", type=int, default=1024,
                    help="hidden width of the serving bench model")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.verify_node:
        if args.store is None:
            ap.error("--verify-node requires --store")
        return run_verify_node(args)
    return run_sweep(args, smoke=args.smoke)


if __name__ == "__main__":
    sys.exit(main())
