"""Nearest-neighbor retrieval: interleaved A/B + cluster chaos soak.

The claims under test (retrieval/):

- **throughput**: the jitted fused distance+top-k path (one matmul +
  in-graph ``lax.top_k``; only k ids + k distances leave the device)
  beats the host VPTree walk — >= 10x queries/s at batch >= 64 in the
  full run on CPU, >= 1x in the CI smoke. The comparison is
  worst-case to worst-case over the SAME corpus: the tree's latency
  is query-dependent (near-duplicate probes of a well-separated
  corpus prune superbly; a query without that structure collapses the
  triangle-inequality bound and the walk degenerates to the O(corpus)
  Python scan), while the fused scan is query-invariant by
  construction. A serving tier provisions for the query that prunes
  nothing, so the gated pair is (host walk, fused scan) on
  pruning-hostile out-of-distribution queries; the tree's
  easy-probe qps is reported alongside, ungated, to show the spread.
- **recall**: the int8 arm (4x denser corpus + exact f32 host refine)
  and the IVF arm (nprobe routed clusters) both hold recall@10 >= 0.95
  against the exact f32 oracle — quality is a gate, not a footnote.
- **determinism**: repeated queries are bitwise identical, including
  distance ties (the (distance, id) merge order).
- **compile discipline**: zero live compiles after the warmup sweep
  across every arm and batch bucket (watchdog-asserted).
- **bytes/query**: the corpus bytes a query's distance pass must read
  (the memory-bound term): int8 strictly under 0.3x of f32 brute, IVF
  strictly under brute (nprobe/K of the corpus + centroids).

--smoke-cluster adds the multi-node chaos case: two ``serve
--neighbors-index`` subprocesses own disjoint shard slices of one
published index; mid-soak one is SIGKILLed. Gates: every in-flight and
subsequent query is answered — full while both live, ``partial: true``
(never an exception) while the killed node's shards have no owner; the
rejoined node (same id) warms from the shared ArtifactStore with zero
live compiles and full answers resume; the second node SIGTERM-drains
to exit 0 with its record deregistered.

Usage:
    python benchmarks/neighbors.py                 # full A/B table
        # (1M-vector corpus, host VPTree built on ALL of it; the
        # speedup gate is 10x on worst-case queries)
    python benchmarks/neighbors.py --smoke         # CI gate
    python benchmarks/neighbors.py --smoke-cluster # CI chaos gate
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

from benchmarks import ab

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def blob_corpus(n, dim, k_blobs, seed=0, spread=0.15):
    """Seeded mixture-of-gaussians corpus — the clustered geometry of
    real embedding spaces (and what IVF routing exists for)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k_blobs, dim)).astype(np.float32) * 3.0
    assign = rng.integers(k_blobs, size=n)
    pts = centers[assign] + \
        rng.normal(size=(n, dim)).astype(np.float32) * spread
    return pts.astype(np.float32)


def exact_oracle(corpus, queries, k, block=4096):
    """Exact f32 top-k by blocked full scan (the recall ground truth;
    blocked so the 1M full run fits in ram)."""
    b = queries.shape[0]
    best_d = np.full((b, k), np.inf, np.float32)
    best_i = np.full((b, k), -1, np.int64)
    q2 = np.sum(queries ** 2, axis=1, keepdims=True)
    for lo in range(0, corpus.shape[0], block):
        c = corpus[lo:lo + block]
        d2 = q2 - 2.0 * (queries @ c.T) + np.sum(c ** 2, axis=1)[None]
        d = np.concatenate([best_d, d2.astype(np.float32)], axis=1)
        i = np.concatenate(
            [best_i, np.arange(lo, lo + c.shape[0])[None].repeat(
                b, axis=0)], axis=1)
        order = np.argsort(d, axis=1, kind="stable")[:, :k]
        best_d = np.take_along_axis(d, order, axis=1)
        best_i = np.take_along_axis(i, order, axis=1)
    return best_d, best_i


def recall_at(found, oracle):
    hits = sum(len(set(int(v) for v in f if v >= 0)
                   & set(int(v) for v in o))
               for f, o in zip(found, oracle))
    return hits / float(oracle.size)


def _bytes_per_query(index, mode):
    """Corpus bytes the distance pass reads per query — the
    memory-bound cost term (metadata like scales/ids excluded; they
    are O(R) vs the O(R*D) row term)."""
    elt = 1 if index.precision == "int8" else 4
    rows_bytes = index.shard_rows * index.dim * elt
    n_shards = len(index.shards)
    if mode == "brute":
        return n_shards * rows_bytes
    probe = min(index.ivf.get("nprobe_hint", 8), index.ivf["clusters"])
    per_shard = (index.ivf["clusters"] * index.dim * 4      # centroids
                 + probe * index.ivf["cap"] * index.dim * elt)
    return n_shards * per_shard


# ---- single-process A/B ---------------------------------------------------

def run_ab(args, smoke: bool) -> int:
    from deeplearning4j_tpu.clustering.vptree import VPTree
    from deeplearning4j_tpu.retrieval.engine import RetrievalEngine
    from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex

    n = 20000 if smoke else args.vectors
    dim = 32 if smoke else args.dim
    batch = args.batch
    k = 10
    rounds = 3 if smoke else args.rounds
    # nprobe must scale with the blob/cluster ratio to hold the recall
    # gate: the full corpus packs ~488 blobs into 256 clusters/shard,
    # so the capacity-balanced assignment spills dense-blob fringe rows
    # into neighboring clusters and shallow probing misses them
    # (measured on the 1M index: recall@10 0.941 at nprobe=32, 0.991
    # at 64). 8 of 64 clusters suffices on the small smoke corpus.
    nprobe = 8 if smoke else 64

    print(f"neighbors A/B: corpus {n}x{dim}, batch {batch}, k={k}, "
          f"{rounds} interleaved rounds")
    corpus = blob_corpus(n, dim, k_blobs=max(16, n // 2048),
                         seed=args.seed)
    rng = np.random.default_rng(args.seed + 1)
    probes = corpus[rng.integers(n, size=batch)] + rng.normal(
        size=(batch, dim)).astype(np.float32) * 0.05
    # pruning-hostile queries for the worst-case pair: scaled like the
    # blob centers but unrelated to any of them, so the walk's tau
    # never collapses and the tree degenerates to the O(corpus) scan
    worst = rng.normal(size=(batch, dim)).astype(np.float32) * 3.0
    _, oracle = exact_oracle(corpus, probes, k)

    shard_rows = min(n, 8192 if smoke else 262144)
    ivf_clusters = 64 if smoke else 256
    print("building indexes (f32, int8, ivf, ivf-int8)...")
    arms = {}
    for name, precision, ivf in (
            ("brute-f32", "f32", 0), ("brute-int8", "int8", 0),
            ("ivf-f32", "f32", ivf_clusters),
            ("ivf-int8", "int8", ivf_clusters)):
        idx = ShardedCorpusIndex.build(
            corpus, shard_rows=shard_rows, precision=precision,
            ivf_clusters=ivf, nprobe_hint=nprobe, seed=args.seed)
        eng = RetrievalEngine(idx, k_ladder=(10, 40), max_batch=batch,
                              session_id=f"bench-{name}")
        eng.warmup()
        mode = "ivf" if ivf else "brute"
        arms[name] = (eng, mode, _bytes_per_query(idx, mode))

    # the host baseline walks the SAME corpus — no subsampling
    t0 = time.perf_counter()
    tree = VPTree(corpus)
    print(f"  host VPTree built on all {n} rows in "
          f"{time.perf_counter() - t0:.1f}s")

    # interleaved rounds (benchmarks/ab.py): arm order rotates so drift
    # (thermal, page cache) spreads across arms instead of biasing the
    # last one
    def _engine_arm(name):
        eng, mode, _ = arms[name]

        def go(_r):
            t0 = time.perf_counter()
            eng.search(probes, k, mode=mode)
            return batch / (time.perf_counter() - t0)
        return go

    def _host_arm(_r):
        t0 = time.perf_counter()
        for qv in probes:
            tree.search(qv, k)
        return batch / (time.perf_counter() - t0)

    ab_arms = {name: _engine_arm(name) for name in arms}
    ab_arms["host-vptree"] = _host_arm
    order = list(ab_arms)
    stats = ab.interleaved(ab_arms, rounds)

    # the gated worst-case pair: same pruning-hostile queries through
    # both arms. The fused scan's cost is query-invariant (same matmul
    # regardless of the query); the tree's is not — this is the number
    # a serving tier provisions for.
    n_worst = 8 if smoke else 4
    t0 = time.perf_counter()
    for qv in worst[:n_worst]:
        tree.search(qv, k)
    host_worst_qps = n_worst / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    arms["brute-f32"][0].search(worst, k, mode="brute")
    fused_worst_qps = batch / (time.perf_counter() - t0)

    failures = []
    rows = []
    host_qps = float(np.median(stats["host-vptree"]))
    for name in order:
        qps = float(np.median(stats[name]))
        if name == "host-vptree":
            rows.append((name, qps, None, n * dim * 8, 1.0))
            continue
        eng, mode, bpq = arms[name]
        d1, i1 = eng.search(probes, k, mode=mode)
        d2, i2 = eng.search(probes, k, mode=mode)
        if not (np.asarray(d1).tobytes() == np.asarray(d2).tobytes()
                and np.asarray(i1).tobytes()
                == np.asarray(i2).tobytes()):
            failures.append(f"{name}: repeat not bitwise identical")
        rec = recall_at(np.asarray(i1), oracle)
        rows.append((name, qps, rec, bpq, qps / host_qps))
        if rec < 0.95:
            failures.append(
                f"{name}: recall@10 {rec:.3f} below the 0.95 gate")
        if eng.recompiles_after_warmup:
            failures.append(
                f"{name}: {eng.recompiles_after_warmup} live "
                f"compile(s) after warmup")
        p = eng.query_ring.quantiles((0.5, 0.99))
        print(f"  {name:<12} qps={qps:10.1f}  "
              f"p50={p[0.5] * 1e3:7.2f}ms  p99={p[0.99] * 1e3:7.2f}ms"
              f"  recall@10={rec:.3f}  bytes/q={bpq / 1e6:8.2f}MB"
              f"  vs-host={qps / host_qps:6.1f}x")
    print(f"  {'host-vptree':<12} qps={host_qps:10.1f}  "
          f"(exact walk, easy in-distribution probes — ungated)")
    print(f"  worst-case queries: host walk {host_worst_qps:8.2f} q/s"
          f"  vs fused scan {fused_worst_qps:8.1f} q/s "
          f"({fused_worst_qps / host_worst_qps:.1f}x)")

    speedup_gate = 1.0 if smoke else 10.0
    if fused_worst_qps < speedup_gate * host_worst_qps:
        failures.append(
            f"fused scan {fused_worst_qps:.0f} q/s under "
            f"{speedup_gate}x the host walk ({host_worst_qps:.2f} "
            f"q/s) on worst-case queries")
    f32_bytes = arms["brute-f32"][2]
    if arms["brute-int8"][2] > 0.3 * f32_bytes:
        failures.append("int8 bytes/query not under 0.3x of f32")
    if arms["ivf-f32"][2] >= f32_bytes:
        failures.append("IVF bytes/query not under brute f32")

    label = "smoke" if smoke else "full"
    if failures:
        print(f"neighbors {label}: FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    if not smoke:
        # the full acceptance serves the 1M index through the real
        # HTTP ingress: same engine behind a FleetRouter pool +
        # /api/neighbors, answers must match the direct search
        from deeplearning4j_tpu.parallel.fleet import FleetRouter
        from deeplearning4j_tpu.ui.neighbors_module import \
            NeighborsModule
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
        eng = arms["brute-f32"][0]
        router = FleetRouter(session_id="nn-bench")
        router.add_retrieval_pool("neighbors", eng)
        server = UIServer(port=0)
        server.attach(InMemoryStatsStorage())
        server.register_module(NeighborsModule(router))
        server.start()
        try:
            body = json.dumps({"queries": probes.tolist(),
                               "k": k}).encode()
            req = urllib.request.Request(
                f"{server.url}/api/neighbors", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
            d_ref, i_ref = eng.search(probes, k, mode="brute")
            if not np.array_equal(np.asarray(out["ids"]),
                                  np.asarray(i_ref)):
                failures.append("/api/neighbors ids diverge from the "
                                "direct engine search")
            else:
                print(f"  /api/neighbors served the {n}-vector index: "
                      f"{out['n']} queries, index_version="
                      f"{out['index_version']}")
        finally:
            server.stop()
        if failures:
            print(f"neighbors {label}: FAIL")
            for f in failures:
                print(f"  - {f}")
            return 1

    print(f"neighbors {label}: PASS — fused >= {speedup_gate}x host, "
          f"recall gates held, bitwise-deterministic, zero live "
          f"compiles after warmup")
    return 0


# ---- cluster chaos smoke --------------------------------------------------

def _start_nn_node(node_id, shards, reg_dir, store_dir, key, log_path):
    cmd = [sys.executable, "-m", "deeplearning4j_tpu", "serve",
           "--neighbors-index", key, "--artifact-store", store_dir,
           "--neighbors-shards", ",".join(str(s) for s in shards),
           "--neighbors-k-ladder", "10,40", "--neighbors-batch", "16",
           "--ui-port", "0", "--join", reg_dir, "--node-id", node_id,
           "--drain-timeout", "20"]
    log = open(log_path, "w")
    return subprocess.Popen(cmd, cwd=_ROOT, stdout=log,
                            stderr=subprocess.STDOUT), log


def _wait_nn_node(registry, node_id, pid, timeout_s=240.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        rec = registry.read_all().get(node_id)
        if rec and rec.get("pid") == pid \
                and (rec.get("stats") or {}).get("shards"):
            return rec
        time.sleep(0.2)
    raise RuntimeError(f"node {node_id} (pid {pid}) never gossiped "
                       f"its shards")


def _tail(path, n=2000):
    try:
        with open(path) as f:
            return f.read()[-n:]
    except OSError:
        return "<no log>"


def run_cluster(args, smoke: bool = True) -> int:
    """Mid-query node-SIGKILL chaos through the scatter-gather tier
    (the module docstring's --smoke-cluster contract)."""
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from deeplearning4j_tpu.parallel.aot_cache import ArtifactStore
    from deeplearning4j_tpu.parallel.node import NodeRegistry
    from deeplearning4j_tpu.retrieval.cluster import NeighborsDispatcher
    from deeplearning4j_tpu.retrieval.index import ShardedCorpusIndex

    n, dim, k = 8192, 32, 10
    kill_after = 3.0 if smoke else 8.0
    dead_tail_s = 4.0
    rejoin_tail_s = 5.0

    work = tempfile.mkdtemp(prefix="dl4j-nn-cluster-")
    reg_dir = os.path.join(work, "registry")
    store_dir = os.path.join(work, "store")
    corpus = blob_corpus(n, dim, k_blobs=32, seed=args.seed)
    ShardedCorpusIndex.build(corpus, shard_rows=2048,
                             precision="int8").save(
        ArtifactStore(store_dir), "nnbench")
    registry = NodeRegistry(reg_dir, stale_after_s=1.0,
                            dead_after_s=2.5)
    rng = np.random.default_rng(args.seed)
    probes = corpus[rng.integers(n, size=8)] + rng.normal(
        size=(8, dim)).astype(np.float32) * 0.05

    logs = {"a": os.path.join(work, "a.log"),
            "b": os.path.join(work, "b.log")}
    handles = []
    failures = []
    pa, log = _start_nn_node("a", [0, 1], reg_dir, store_dir,
                             "nnbench", logs["a"])
    handles.append(log)
    pb = None
    try:
        _wait_nn_node(registry, "a", pa.pid)
        pb, log = _start_nn_node("b", [2, 3], reg_dir, store_dir,
                                 "nnbench", logs["b"])
        handles.append(log)
        rec_b = _wait_nn_node(registry, "b", pb.pid)

        disp = NeighborsDispatcher(
            registry, timeout_s=10.0, retries=2, backoff_s=0.05,
            breaker_failures=3, breaker_reset_s=1.0)
        counts = {"full": 0, "partial": 0, "error": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def one():
            try:
                out = disp.search(probes, k)
                with lock:
                    counts["partial" if out["partial"]
                           else "full"] += 1
            except Exception:
                with lock:
                    counts["error"] += 1

        pool = ThreadPoolExecutor(max_workers=16)
        futs = []
        arrival = random.Random(args.seed)

        def drive():
            while not stop.is_set():
                futs.append(pool.submit(one))
                time.sleep(arrival.expovariate(30.0))

        driver = threading.Thread(target=drive, daemon=True)
        driver.start()

        time.sleep(kill_after)
        before_kill = dict(counts)
        pa.kill()                                    # SIGKILL node a
        print(f"  SIGKILL node a at t={kill_after}s "
              f"(answers so far: {before_kill})")
        time.sleep(dead_tail_s)
        during = {kk: counts[kk] - before_kill[kk] for kk in counts}
        if during["error"]:
            failures.append(
                f"{during['error']} queries raised during the dead "
                f"window — contract is full or partial, never an "
                f"exception")
        if not during["partial"]:
            failures.append(
                "no partial answers during the dead window — the "
                "degradation path never exercised")

        # rejoin under the SAME id: stale-record overwrite + warm from
        # the shared store
        t_join = time.time()
        pa2, log = _start_nn_node("a", [0, 1], reg_dir, store_dir,
                                  "nnbench", logs["a"] + ".2")
        handles.append(log)
        rec_a2 = _wait_nn_node(registry, "a", pa2.pid)
        rejoin_s = time.time() - t_join
        time.sleep(rejoin_tail_s)
        stop.set()
        driver.join(timeout=10)
        for f in futs:
            f.result()

        # the rejoined node must answer full again and be warm with
        # zero live compiles (the store's XLA cache fed its warmup)
        out = disp.search(probes, k)
        if out["partial"]:
            failures.append("post-rejoin query still partial")
        with urllib.request.urlopen(
                rec_a2["url"] + "/api/neighbors/stats",
                timeout=10) as r:
            st = json.loads(r.read())["engine"]
        if not st["warm"] or st["recompiles_after_warmup"]:
            failures.append(
                f"rejoined node not cleanly warm: warm={st['warm']} "
                f"recompiles={st['recompiles_after_warmup']}")
        oracle_d, oracle_i = exact_oracle(corpus, probes, k)
        rec = recall_at(np.asarray(out["ids"]), oracle_i)
        if rec < 0.95:
            failures.append(f"post-rejoin recall {rec:.3f} < 0.95")

        # SIGTERM drain on b: finish in-flight, deregister, exit 0
        pb.terminate()
        rc = pb.wait(timeout=30)
        if rc != 0:
            failures.append(f"node b drain exited {rc}")
        if "b" in registry.read_all():
            failures.append("node b record not deregistered")
        disp.shutdown()

        print(f"  answers: {counts}  (dead window: {during}, "
              f"rejoin {rejoin_s:.1f}s)")
        if failures:
            print("neighbors cluster smoke: FAIL")
            for f in failures:
                print(f"  - {f}")
            for nid, p in logs.items():
                print(f"--- tail {nid} ---\n{_tail(p)}")
            return 1
        print("neighbors cluster smoke: PASS — every query answered "
              "full or flagged-partial through the SIGKILL, rejoiner "
              "warm from the store with zero live compiles, drain "
              "clean")
        return 0
    finally:
        for p in (pa, pb, locals().get("pa2")):
            if p is not None and p.poll() is None:
                p.kill()
        for h in handles:
            h.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--smoke-cluster", action="store_true")
    ap.add_argument("--vectors", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.smoke_cluster:
        return run_cluster(args, smoke=True)
    return run_ab(args, smoke=args.smoke)


if __name__ == "__main__":
    sys.exit(main())
