"""Bound the ResNet50 win available from fusing BN batch-stat traffic.

Three step variants at the bench config (batch 256, K steps/dispatch):
  base    — real BatchNormalization (batch stats fwd, recomputed bwd)
  frozen  — BN uses running stats (pure elementwise; XLA fuses it into
            neighbors completely). Upper bound for ANY conv+BN fusion
            kernel: no fusion can beat deleting the stats entirely.
  nobn    — BN replaced by identity. Bounds the whole BN cost incl. the
            scale/shift elementwise math.

If frozen ≈ base, the Pallas conv+BN fusion lever is dead and the
remaining gap is conv-intrinsic; if frozen >> base, the kernel is worth
building (VERDICT r3 #1).
"""

import dataclasses as dc
import json
import time

import numpy as np


def build_step(mode: str, batch: int, k: int):
    import jax.numpy as jnp
    import jax.random as jrandom
    from deeplearning4j_tpu.nn.layers import normalization as nz
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step
    from deeplearning4j_tpu.optimize.updaters import Nesterovs
    from deeplearning4j_tpu.zoo.models import ResNet50

    orig_apply = nz.BatchNormalization.apply
    if mode == "frozen":
        def patched(self, params, state, x, ctx):
            return orig_apply(dc.replace(self, use_global_stats_in_train=True),
                              params, state, x, ctx)
        nz.BatchNormalization.apply = patched
    elif mode == "nobn":
        def patched(self, params, state, x, ctx):
            return x, state
        nz.BatchNormalization.apply = patched
    try:
        model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                         compute_dtype="bfloat16",
                         updater=Nesterovs(1e-2, 0.9)).init()

        def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
            return model._loss(params, mstate, (feats,), (labels,), fmask,
                               lmask, rng, it)

        steps_fn = make_scan_train_step(loss_fn, model._tx)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3))
                        .astype(np.float32))
        y = np.zeros((batch, 200), np.float32)
        y[np.arange(batch), rng.integers(0, 200, batch)] = 1.0
        xs = jnp.broadcast_to(x, (k,) + x.shape)
        ys = jnp.broadcast_to(jnp.asarray(y), (k, batch, 200))
        key = jrandom.PRNGKey(0)
        ts = model.train_state
        ts, losses = steps_fn(ts, xs, ys, None, None, key)
        float(np.asarray(losses[-1]))
        n = 3
        t0 = time.perf_counter()
        for i in range(n):
            ts, losses = steps_fn(ts, xs, ys, None, None,
                                  jrandom.fold_in(key, i))
        float(np.asarray(losses[-1]))
        dt = time.perf_counter() - t0
        return n * k * batch / dt
    finally:
        nz.BatchNormalization.apply = orig_apply


if __name__ == "__main__":
    batch, k = 256, 64
    for mode in ("base", "frozen", "nobn"):
        ips = build_step(mode, batch, k)
        print(json.dumps({"mode": mode, "batch": batch, "k": k,
                          "img_per_sec": round(ips, 1)}))
