// dl4j_tpu native host runtime — C ABI, loaded via ctypes.
//
// TPU-native counterpart of the reference's native host-side components
// (SURVEY §2.14): the libnd4j ThresholdCompression encode/decode pair
// (used by EncodedGradientsAccumulator.java:255-292) and the DataVec
// record-reading hot loops (CSV text -> float tensors, IDX image files)
// that feed device infeed. Device math stays in XLA/Pallas; this library
// only accelerates the host paths that would otherwise bottleneck ETL or
// DCN gradient exchange.
//
// Build: `make` in this directory (g++ -O3 -shared). The Python wrapper
// (deeplearning4j_tpu/utils/native.py) builds on demand and falls back to
// pure numpy when no toolchain is present.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Threshold codec (1-bit gradient compression wire format)
//   message layout (int32 words):
//     [kind, length, n_payload, payload...]
//   kind 0 = FLEXIBLE (sparse signed indices: (idx+1)*sign)
//   kind 1 = BITMAP   (2 bits/element, 16 elements per word: 01=+1, 10=-1)
// ---------------------------------------------------------------------------

static const int32_t FLEXIBLE = 0;
static const int32_t BITMAP = 1;

// Returns message length in int32 words (<= 3 + n).
int64_t dl4j_encode_flexible(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t w = 3;
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i) {
        int8_t s = signs[i];
        if (s != 0) {
            out[w++] = (int32_t)((i + 1) * (s > 0 ? 1 : -1));
            ++nnz;
        }
    }
    out[0] = FLEXIBLE;
    out[1] = (int32_t)n;
    out[2] = (int32_t)nnz;
    return w;
}

int64_t dl4j_encode_bitmap(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t n_words = (n + 15) / 16;
    out[0] = BITMAP;
    out[1] = (int32_t)n;
    out[2] = (int32_t)n_words;
    for (int64_t wi = 0; wi < n_words; ++wi) {
        uint32_t word = 0;
        int64_t base = wi * 16;
        int64_t lim = (n - base) < 16 ? (n - base) : 16;
        for (int64_t j = 0; j < lim; ++j) {
            int8_t s = signs[base + j];
            uint32_t code = s > 0 ? 1u : (s < 0 ? 2u : 0u);
            word |= code << (2 * j);
        }
        out[3 + wi] = (int32_t)word;
    }
    return 3 + n_words;
}

// Auto-select codec by density (cutoff 2/32 as in the reference's native
// ThresholdCompression: index list = 32 bits/nnz vs bitmap = 2 bits/elem).
int64_t dl4j_encode(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i)
        nnz += signs[i] != 0;
    if (nnz * 32 > n * 2)
        return dl4j_encode_bitmap(signs, n, out);
    return dl4j_encode_flexible(signs, n, out);
}

// Returns decoded length, or -1 on malformed input.
int64_t dl4j_decode(const int32_t* msg, int64_t msg_len, int8_t* out,
                    int64_t max_out) {
    if (msg_len < 3) return -1;
    int32_t kind = msg[0];
    int64_t n = msg[1];
    if (n < 0 || n > max_out) return -1;
    std::memset(out, 0, (size_t)n);
    if (kind == FLEXIBLE) {
        int64_t nnz = msg[2];
        if (msg_len < 3 + nnz) return -1;
        for (int64_t i = 0; i < nnz; ++i) {
            int32_t e = msg[3 + i];
            int64_t idx = (e > 0 ? e : -e) - 1;
            if (idx < 0 || idx >= n) return -1;
            out[idx] = e > 0 ? 1 : -1;
        }
    } else if (kind == BITMAP) {
        int64_t n_words = msg[2];
        if (msg_len < 3 + n_words) return -1;
        for (int64_t wi = 0; wi < n_words; ++wi) {
            uint32_t word = (uint32_t)msg[3 + wi];
            int64_t base = wi * 16;
            int64_t lim = (n - base) < 16 ? (n - base) : 16;
            for (int64_t j = 0; j < lim; ++j) {
                uint32_t code = (word >> (2 * j)) & 3u;
                out[base + j] = code == 1 ? 1 : (code == 2 ? -1 : 0);
            }
        }
    } else {
        return -1;
    }
    return n;
}

// Fused: decode message and accumulate signs*threshold into a float
// buffer (the EncodedGradientsAccumulator apply path — one pass, no
// intermediate sign array).
int64_t dl4j_decode_axpy(const int32_t* msg, int64_t msg_len,
                         float threshold, float* acc, int64_t acc_len) {
    if (msg_len < 3) return -1;
    int32_t kind = msg[0];
    int64_t n = msg[1];
    if (n < 0 || n > acc_len) return -1;
    if (kind == FLEXIBLE) {
        int64_t nnz = msg[2];
        if (msg_len < 3 + nnz) return -1;
        for (int64_t i = 0; i < nnz; ++i) {
            int32_t e = msg[3 + i];
            int64_t idx = (e > 0 ? e : -e) - 1;
            if (idx < 0 || idx >= n) return -1;
            acc[idx] += e > 0 ? threshold : -threshold;
        }
    } else if (kind == BITMAP) {
        int64_t n_words = msg[2];
        if (msg_len < 3 + n_words) return -1;
        for (int64_t wi = 0; wi < n_words; ++wi) {
            uint32_t word = (uint32_t)msg[3 + wi];
            int64_t base = wi * 16;
            int64_t lim = (n - base) < 16 ? (n - base) : 16;
            for (int64_t j = 0; j < lim; ++j) {
                uint32_t code = (word >> (2 * j)) & 3u;
                if (code == 1) acc[base + j] += threshold;
                else if (code == 2) acc[base + j] -= threshold;
            }
        }
    } else {
        return -1;
    }
    return n;
}

// ---------------------------------------------------------------------------
// CSV record reader (DataVec CSVRecordReader hot loop)
// Parses a delimited numeric text buffer into a float32 matrix.
// ---------------------------------------------------------------------------

// Counts rows (non-empty lines). Fills n_cols from the first row.
int64_t dl4j_csv_dims(const char* data, int64_t len, char delim,
                      int64_t* n_cols) {
    int64_t rows = 0;
    int64_t cols = 0;
    int64_t cur_cols = 0;
    bool in_row = false;
    for (int64_t i = 0; i < len; ++i) {
        char c = data[i];
        if (c == '\n') {
            if (in_row) {
                ++rows;
                ++cur_cols;
                if (cols == 0) cols = cur_cols;
            }
            cur_cols = 0;
            in_row = false;
        } else if (c == delim) {
            if (in_row) ++cur_cols;
        } else if (c != '\r') {
            in_row = true;
        }
    }
    if (in_row) {
        ++rows;
        ++cur_cols;
        if (cols == 0) cols = cur_cols;
    }
    *n_cols = cols;
    return rows;
}

// Parses into out[rows*cols]; returns rows parsed or -1 on ragged rows /
// unparsable fields.
int64_t dl4j_csv_parse(const char* data, int64_t len, char delim,
                       float* out, int64_t max_rows, int64_t n_cols) {
    int64_t row = 0;
    int64_t col = 0;
    const char* p = data;
    const char* end = data + len;
    char buf[64];
    while (p < end && row < max_rows) {
        // skip blank lines
        while (p < end && (*p == '\n' || *p == '\r')) ++p;
        if (p >= end) break;
        col = 0;
        while (p < end && *p != '\n') {
            const char* field = p;
            while (p < end && *p != delim && *p != '\n' && *p != '\r') ++p;
            int64_t flen = p - field;
            if (flen >= (int64_t)sizeof(buf)) return -1;
            std::memcpy(buf, field, (size_t)flen);
            buf[flen] = 0;
            char* endp = nullptr;
            float v = std::strtof(buf, &endp);
            if (endp == buf && flen > 0) return -1;
            if (col >= n_cols) return -1;
            out[row * n_cols + col] = v;
            ++col;
            if (p < end && *p == delim) ++p;
            while (p < end && *p == '\r') ++p;
        }
        if (col != n_cols) return -1;
        ++row;
    }
    return row;
}

// ---------------------------------------------------------------------------
// IDX (MNIST/EMNIST container) decoder: big-endian header + u8 payload
// scaled to [0,1] float32. (MnistDataFetcher's binary reader.)
// ---------------------------------------------------------------------------

static uint32_t be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

// Returns element count written to out, or -1. dims_out must hold 4.
int64_t dl4j_idx_decode(const uint8_t* data, int64_t len, float* out,
                        int64_t max_out, int64_t* dims_out,
                        int64_t* n_dims_out) {
    if (len < 4) return -1;
    if (data[0] != 0 || data[1] != 0) return -1;
    uint8_t dtype = data[2];
    uint8_t nd = data[3];
    if (dtype != 0x08 || nd < 1 || nd > 4) return -1;  // u8 only
    if (len < 4 + 4 * (int64_t)nd) return -1;
    int64_t total = 1;
    for (int i = 0; i < nd; ++i) {
        dims_out[i] = be32(data + 4 + 4 * i);
        total *= dims_out[i];
    }
    *n_dims_out = nd;
    if (total > max_out || len < 4 + 4 * nd + total) return -1;
    const uint8_t* payload = data + 4 + 4 * nd;
    for (int64_t i = 0; i < total; ++i)
        out[i] = (float)payload[i] * (1.0f / 255.0f);
    return total;
}

// ---------------------------------------------------------------------------
// Fused pair generation for the Word2Vec/ParagraphVectors host producer
// (the work SequenceVectors._window_slabs + skipgram.draw_negatives do in
// numpy — the reference keeps this loop native too, SkipGram.java:176).
//
// All randomness is COUNTER-BASED splitmix64: draw k of a stream is
// mix(seed + (k+1)*GOLDEN), so the numpy fallback
// (deeplearning4j_tpu/nlp/pairgen.py) reproduces the exact same stream
// with vectorized uint64 ops — native and fallback are bitwise-equal by
// construction, and a slab can be regenerated from (seed, indices) alone.
// Draw-index contract (per epoch, shared with the Python fallback):
//   subsample: token's corpus index; window: kept-token index;
//   negatives: pair_index * n_neg + slot (primary stream), same index on
//   the redraw stream; a double collision cycles to (positive+1)%vocab —
//   skipgram.draw_negatives' policy.
// ---------------------------------------------------------------------------

static inline uint64_t sm_mix(uint64_t z) {
    z ^= z >> 30;
    z *= 0xBF58476D1CE4E5B9ULL;
    z ^= z >> 27;
    z *= 0x94D049BB133111EBULL;
    z ^= z >> 31;
    return z;
}

static inline uint64_t sm_draw(uint64_t seed, uint64_t k) {
    return sm_mix(seed + (k + 1) * 0x9E3779B97F4A7C15ULL);
}

// 53-bit uniform in [0,1) — numpy's random() construction, so the
// fallback's (draw >> 11) * 2**-53 compares bitwise-equal.
static inline double sm_unit(uint64_t x) {
    return (double)(x >> 11) * (1.0 / 9007199254740992.0);
}

// Range reduction into [0, m), m < 2^32: multiply-shift on the draw's
// top 32 bits instead of '%', which costs a hardware divide per draw
// on the hot path. (top32 * m) < 2^64, so the numpy fallback computes
// the identical value in plain uint64 arithmetic.
static inline uint64_t sm_range(uint64_t draw, uint64_t m) {
    return ((draw >> 32) * m) >> 32;
}

// Raw draws out[i] = draw(seed, start+i) — the parity-test probe.
void dl4j_sm64_fill(uint64_t seed, int64_t start, int64_t n,
                    uint64_t* out) {
    for (int64_t i = 0; i < n; ++i)
        out[i] = sm_draw(seed, (uint64_t)(start + i));
}

// Frequent-word subsampling over the flat encoded corpus: keep token i
// iff unit(draw(seed, i)) < keep_p[ids[i]]. Writes a 0/1 mask, returns
// the kept count.
int64_t dl4j_pairgen_subsample(const int32_t* ids, int64_t n,
                               const double* keep_p, uint64_t seed,
                               uint8_t* out_keep) {
    int64_t kept = 0;
    for (int64_t i = 0; i < n; ++i) {
        uint8_t k =
            sm_unit(sm_draw(seed, (uint64_t)i)) < keep_p[ids[i]] ? 1 : 0;
        out_keep[i] = k;
        kept += k;
    }
    return kept;
}

// Negative-table draws for pairs [pair_base, pair_base+n): n_neg per
// pair, collision with the pair's positive redrawn once from the second
// stream, a double collision cycled to (positive+1) % max(n_words, 2).
void dl4j_pairgen_negatives(const int32_t* table, int64_t tlen,
                            const int32_t* positive, int64_t n,
                            int32_t n_neg, int32_t n_words,
                            uint64_t nseed, uint64_t n2seed,
                            int64_t pair_base, int32_t* out) {
    int32_t cyc = n_words > 2 ? n_words : 2;
    for (int64_t i = 0; i < n; ++i) {
        int32_t pos = positive[i];
        int32_t* row = out + i * n_neg;
        uint64_t q0 = (uint64_t)((pair_base + i) * n_neg);
        for (int32_t k = 0; k < n_neg; ++k) {
            uint64_t q = q0 + (uint64_t)k;
            int32_t neg = table[(int64_t)
                sm_range(sm_draw(nseed, q), (uint64_t)tlen)];
            if (neg == pos) {
                neg = table[(int64_t)
                    sm_range(sm_draw(n2seed, q), (uint64_t)tlen)];
                if (neg == pos) neg = (pos + 1) % cyc;
            }
            row[k] = neg;
        }
    }
    return;
}

// The fused SGNS/HS/DBOW window walk over kept-token slab [lo, hi):
// per center t an effective window b = 1 + range(draw(wseed, t), window)
// (word2vec.c's randomized b), pairs emitted in ascending-offset order
// (-b..-1, 1..b) clipped to the sequence — identical to the numpy
// producer's offsets-grid flatten. ids/pos/len span the WHOLE kept
// corpus (contexts cross slab bounds, never sequence bounds). With
// n_neg > 0 the negative-table draws are fused into the same pass
// (out_negs row-major [n_pairs, n_neg]). Returns the pair count;
// caller sizes outputs for (hi-lo) * 2*window.
int64_t dl4j_pairgen_walk(const int32_t* ids, const int32_t* pos,
                          const int32_t* len, int64_t lo, int64_t hi,
                          int32_t window, uint64_t wseed,
                          const int32_t* table, int64_t tlen,
                          int32_t n_neg, int32_t n_words,
                          uint64_t nseed, uint64_t n2seed,
                          int64_t pair_base,
                          int32_t* out_center, int32_t* out_context,
                          int32_t* out_negs) {
    int64_t n_pairs = 0;
    int32_t cyc = n_words > 2 ? n_words : 2;
    for (int64_t t = lo; t < hi; ++t) {
        int32_t b = window > 1
            ? (int32_t)(1 + sm_range(sm_draw(wseed, (uint64_t)t),
                                     (uint64_t)window))
            : 1;
        int32_t p = pos[t];
        int32_t L = len[t];
        int32_t c = ids[t];
        int32_t o_lo = (-b > -p) ? -b : -p;             // max(-b, -p)
        int32_t o_hi = (b < L - 1 - p) ? b : L - 1 - p;  // min(b, ...)
        for (int32_t o = o_lo; o <= o_hi; ++o) {
            if (o == 0) continue;
            int32_t ctx = ids[t + o];
            out_center[n_pairs] = c;
            out_context[n_pairs] = ctx;
            if (n_neg > 0) {
                int32_t* row = out_negs + n_pairs * n_neg;
                uint64_t q0 =
                    (uint64_t)((pair_base + n_pairs) * n_neg);
                for (int32_t k = 0; k < n_neg; ++k) {
                    uint64_t q = q0 + (uint64_t)k;
                    int32_t neg = table[(int64_t)
                        sm_range(sm_draw(nseed, q), (uint64_t)tlen)];
                    if (neg == ctx) {
                        neg = table[(int64_t)
                            sm_range(sm_draw(n2seed, q),
                                     (uint64_t)tlen)];
                        if (neg == ctx) neg = (ctx + 1) % cyc;
                    }
                    row[k] = neg;
                }
            }
            ++n_pairs;
        }
    }
    return n_pairs;
}

// CBOW row walk: one row per center with >= 1 valid context. Row
// layout matches the numpy producer exactly: column j holds
// ids[clip(t + offset_j, 0, n_total-1)] for offsets (-W..-1, 1..W)
// with a 0/1 float mask (clipped out-of-window columns carry the
// clipped id under mask 0, as numpy's grid-clip does). Negatives
// (n_neg > 0) use the ROW index as the pair counter, positive = the
// center. Returns the row count; caller sizes outputs for hi-lo rows.
int64_t dl4j_pairgen_walk_cbow(const int32_t* ids, const int32_t* pos,
                               const int32_t* len, int64_t n_total,
                               int64_t lo, int64_t hi, int32_t window,
                               uint64_t wseed, const int32_t* table,
                               int64_t tlen, int32_t n_neg,
                               int32_t n_words, uint64_t nseed,
                               uint64_t n2seed, int64_t row_base,
                               int32_t* out_ctx, float* out_cmask,
                               int32_t* out_center, int32_t* out_negs) {
    int32_t cw = 2 * window;
    int32_t cyc = n_words > 2 ? n_words : 2;
    int64_t r = 0;
    for (int64_t t = lo; t < hi; ++t) {
        int32_t b = window > 1
            ? (int32_t)(1 + sm_range(sm_draw(wseed, (uint64_t)t),
                                     (uint64_t)window))
            : 1;
        int32_t p = pos[t];
        int32_t L = len[t];
        int32_t* ctxrow = out_ctx + r * cw;
        float* mrow = out_cmask + r * cw;
        int32_t n_valid = 0;
        for (int32_t j = 0; j < cw; ++j) {
            int32_t o = j < window ? j - window : j - window + 1;
            int64_t gi = t + o;
            if (gi < 0) gi = 0;
            if (gi > n_total - 1) gi = n_total - 1;
            ctxrow[j] = ids[gi];
            int32_t po = p + o;
            bool ok = (o >= -b && o <= b && po >= 0 && po < L);
            mrow[j] = ok ? 1.0f : 0.0f;
            n_valid += ok;
        }
        if (n_valid == 0) continue;        // centers without context
        out_center[r] = ids[t];
        if (n_neg > 0) {
            int32_t c = ids[t];
            int32_t* row = out_negs + r * n_neg;
            uint64_t q0 = (uint64_t)((row_base + r) * n_neg);
            for (int32_t k = 0; k < n_neg; ++k) {
                uint64_t q = q0 + (uint64_t)k;
                int32_t neg = table[(int64_t)
                    sm_range(sm_draw(nseed, q), (uint64_t)tlen)];
                if (neg == c) {
                    neg = table[(int64_t)
                        sm_range(sm_draw(n2seed, q), (uint64_t)tlen)];
                    if (neg == c) neg = (c + 1) % cyc;
                }
                row[k] = neg;
            }
        }
        ++r;
    }
    return r;
}

}  // extern "C"
