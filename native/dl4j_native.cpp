// dl4j_tpu native host runtime — C ABI, loaded via ctypes.
//
// TPU-native counterpart of the reference's native host-side components
// (SURVEY §2.14): the libnd4j ThresholdCompression encode/decode pair
// (used by EncodedGradientsAccumulator.java:255-292) and the DataVec
// record-reading hot loops (CSV text -> float tensors, IDX image files)
// that feed device infeed. Device math stays in XLA/Pallas; this library
// only accelerates the host paths that would otherwise bottleneck ETL or
// DCN gradient exchange.
//
// Build: `make` in this directory (g++ -O3 -shared). The Python wrapper
// (deeplearning4j_tpu/utils/native.py) builds on demand and falls back to
// pure numpy when no toolchain is present.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Threshold codec (1-bit gradient compression wire format)
//   message layout (int32 words):
//     [kind, length, n_payload, payload...]
//   kind 0 = FLEXIBLE (sparse signed indices: (idx+1)*sign)
//   kind 1 = BITMAP   (2 bits/element, 16 elements per word: 01=+1, 10=-1)
// ---------------------------------------------------------------------------

static const int32_t FLEXIBLE = 0;
static const int32_t BITMAP = 1;

// Returns message length in int32 words (<= 3 + n).
int64_t dl4j_encode_flexible(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t w = 3;
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i) {
        int8_t s = signs[i];
        if (s != 0) {
            out[w++] = (int32_t)((i + 1) * (s > 0 ? 1 : -1));
            ++nnz;
        }
    }
    out[0] = FLEXIBLE;
    out[1] = (int32_t)n;
    out[2] = (int32_t)nnz;
    return w;
}

int64_t dl4j_encode_bitmap(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t n_words = (n + 15) / 16;
    out[0] = BITMAP;
    out[1] = (int32_t)n;
    out[2] = (int32_t)n_words;
    for (int64_t wi = 0; wi < n_words; ++wi) {
        uint32_t word = 0;
        int64_t base = wi * 16;
        int64_t lim = (n - base) < 16 ? (n - base) : 16;
        for (int64_t j = 0; j < lim; ++j) {
            int8_t s = signs[base + j];
            uint32_t code = s > 0 ? 1u : (s < 0 ? 2u : 0u);
            word |= code << (2 * j);
        }
        out[3 + wi] = (int32_t)word;
    }
    return 3 + n_words;
}

// Auto-select codec by density (cutoff 2/32 as in the reference's native
// ThresholdCompression: index list = 32 bits/nnz vs bitmap = 2 bits/elem).
int64_t dl4j_encode(const int8_t* signs, int64_t n, int32_t* out) {
    int64_t nnz = 0;
    for (int64_t i = 0; i < n; ++i)
        nnz += signs[i] != 0;
    if (nnz * 32 > n * 2)
        return dl4j_encode_bitmap(signs, n, out);
    return dl4j_encode_flexible(signs, n, out);
}

// Returns decoded length, or -1 on malformed input.
int64_t dl4j_decode(const int32_t* msg, int64_t msg_len, int8_t* out,
                    int64_t max_out) {
    if (msg_len < 3) return -1;
    int32_t kind = msg[0];
    int64_t n = msg[1];
    if (n < 0 || n > max_out) return -1;
    std::memset(out, 0, (size_t)n);
    if (kind == FLEXIBLE) {
        int64_t nnz = msg[2];
        if (msg_len < 3 + nnz) return -1;
        for (int64_t i = 0; i < nnz; ++i) {
            int32_t e = msg[3 + i];
            int64_t idx = (e > 0 ? e : -e) - 1;
            if (idx < 0 || idx >= n) return -1;
            out[idx] = e > 0 ? 1 : -1;
        }
    } else if (kind == BITMAP) {
        int64_t n_words = msg[2];
        if (msg_len < 3 + n_words) return -1;
        for (int64_t wi = 0; wi < n_words; ++wi) {
            uint32_t word = (uint32_t)msg[3 + wi];
            int64_t base = wi * 16;
            int64_t lim = (n - base) < 16 ? (n - base) : 16;
            for (int64_t j = 0; j < lim; ++j) {
                uint32_t code = (word >> (2 * j)) & 3u;
                out[base + j] = code == 1 ? 1 : (code == 2 ? -1 : 0);
            }
        }
    } else {
        return -1;
    }
    return n;
}

// Fused: decode message and accumulate signs*threshold into a float
// buffer (the EncodedGradientsAccumulator apply path — one pass, no
// intermediate sign array).
int64_t dl4j_decode_axpy(const int32_t* msg, int64_t msg_len,
                         float threshold, float* acc, int64_t acc_len) {
    if (msg_len < 3) return -1;
    int32_t kind = msg[0];
    int64_t n = msg[1];
    if (n < 0 || n > acc_len) return -1;
    if (kind == FLEXIBLE) {
        int64_t nnz = msg[2];
        if (msg_len < 3 + nnz) return -1;
        for (int64_t i = 0; i < nnz; ++i) {
            int32_t e = msg[3 + i];
            int64_t idx = (e > 0 ? e : -e) - 1;
            if (idx < 0 || idx >= n) return -1;
            acc[idx] += e > 0 ? threshold : -threshold;
        }
    } else if (kind == BITMAP) {
        int64_t n_words = msg[2];
        if (msg_len < 3 + n_words) return -1;
        for (int64_t wi = 0; wi < n_words; ++wi) {
            uint32_t word = (uint32_t)msg[3 + wi];
            int64_t base = wi * 16;
            int64_t lim = (n - base) < 16 ? (n - base) : 16;
            for (int64_t j = 0; j < lim; ++j) {
                uint32_t code = (word >> (2 * j)) & 3u;
                if (code == 1) acc[base + j] += threshold;
                else if (code == 2) acc[base + j] -= threshold;
            }
        }
    } else {
        return -1;
    }
    return n;
}

// ---------------------------------------------------------------------------
// CSV record reader (DataVec CSVRecordReader hot loop)
// Parses a delimited numeric text buffer into a float32 matrix.
// ---------------------------------------------------------------------------

// Counts rows (non-empty lines). Fills n_cols from the first row.
int64_t dl4j_csv_dims(const char* data, int64_t len, char delim,
                      int64_t* n_cols) {
    int64_t rows = 0;
    int64_t cols = 0;
    int64_t cur_cols = 0;
    bool in_row = false;
    for (int64_t i = 0; i < len; ++i) {
        char c = data[i];
        if (c == '\n') {
            if (in_row) {
                ++rows;
                ++cur_cols;
                if (cols == 0) cols = cur_cols;
            }
            cur_cols = 0;
            in_row = false;
        } else if (c == delim) {
            if (in_row) ++cur_cols;
        } else if (c != '\r') {
            in_row = true;
        }
    }
    if (in_row) {
        ++rows;
        ++cur_cols;
        if (cols == 0) cols = cur_cols;
    }
    *n_cols = cols;
    return rows;
}

// Parses into out[rows*cols]; returns rows parsed or -1 on ragged rows /
// unparsable fields.
int64_t dl4j_csv_parse(const char* data, int64_t len, char delim,
                       float* out, int64_t max_rows, int64_t n_cols) {
    int64_t row = 0;
    int64_t col = 0;
    const char* p = data;
    const char* end = data + len;
    char buf[64];
    while (p < end && row < max_rows) {
        // skip blank lines
        while (p < end && (*p == '\n' || *p == '\r')) ++p;
        if (p >= end) break;
        col = 0;
        while (p < end && *p != '\n') {
            const char* field = p;
            while (p < end && *p != delim && *p != '\n' && *p != '\r') ++p;
            int64_t flen = p - field;
            if (flen >= (int64_t)sizeof(buf)) return -1;
            std::memcpy(buf, field, (size_t)flen);
            buf[flen] = 0;
            char* endp = nullptr;
            float v = std::strtof(buf, &endp);
            if (endp == buf && flen > 0) return -1;
            if (col >= n_cols) return -1;
            out[row * n_cols + col] = v;
            ++col;
            if (p < end && *p == delim) ++p;
            while (p < end && *p == '\r') ++p;
        }
        if (col != n_cols) return -1;
        ++row;
    }
    return row;
}

// ---------------------------------------------------------------------------
// IDX (MNIST/EMNIST container) decoder: big-endian header + u8 payload
// scaled to [0,1] float32. (MnistDataFetcher's binary reader.)
// ---------------------------------------------------------------------------

static uint32_t be32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

// Returns element count written to out, or -1. dims_out must hold 4.
int64_t dl4j_idx_decode(const uint8_t* data, int64_t len, float* out,
                        int64_t max_out, int64_t* dims_out,
                        int64_t* n_dims_out) {
    if (len < 4) return -1;
    if (data[0] != 0 || data[1] != 0) return -1;
    uint8_t dtype = data[2];
    uint8_t nd = data[3];
    if (dtype != 0x08 || nd < 1 || nd > 4) return -1;  // u8 only
    if (len < 4 + 4 * (int64_t)nd) return -1;
    int64_t total = 1;
    for (int i = 0; i < nd; ++i) {
        dims_out[i] = be32(data + 4 + 4 * i);
        total *= dims_out[i];
    }
    *n_dims_out = nd;
    if (total > max_out || len < 4 + 4 * nd + total) return -1;
    const uint8_t* payload = data + 4 + 4 * nd;
    for (int64_t i = 0; i < total; ++i)
        out[i] = (float)payload[i] * (1.0f / 255.0f);
    return total;
}

}  // extern "C"
