#!/usr/bin/env python
"""Static checker for host-sync patterns in jit-traced hot paths.

``float(x)``, ``np.asarray(x)`` and ``x.item()`` on a traced jax value
force a device->host transfer (and, inside a jit trace, a
ConcretizationTypeError at best or a silent per-step sync at worst).
The telemetry design (observe/) exists so the train loop does exactly
ONE device fetch per flush interval; a stray ``float(loss)`` in ops/
or the solver undoes that.

This tool greps the hot-path modules -- ``deeplearning4j_tpu/ops/`` and
``deeplearning4j_tpu/optimize/solver.py`` -- for those patterns and
fails if any line matches without an explicit ``# host-sync-ok``
pragma. Trace-time constants (Python ints/floats computed from shapes
or env vars before tracing) are legitimate: annotate them with the
pragma plus a short reason.

Usage:
    python tools/check_host_sync.py            # check the default paths
    python tools/check_host_sync.py --paths a.py dir/   # explicit set

Exit status: 0 when clean, 1 when unallowed hits are found.
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

# hot paths: everything here runs inside (or builds) jitted step
# functions, where a hidden sync is a per-iteration cost
DEFAULT_PATHS = (
    "deeplearning4j_tpu/ops",
    "deeplearning4j_tpu/optimize/solver.py",
    "deeplearning4j_tpu/models",
    # parallel/ includes the serving engine (parallel/serving.py), the
    # fleet router (parallel/fleet.py) and the persisted AOT cache
    # (parallel/aot_cache.py): the only legitimate fetches are the
    # completion-thread block/asarray pair and the cache's one-time
    # startup weights fingerprint (pragma'd there); a sync on the
    # dispatch/admission path would re-serialize the request pipeline
    # the engine exists to overlap
    "deeplearning4j_tpu/parallel",
    # the input-feeder hot path: a stray per-batch host sync here would
    # serialize ETL back onto the step loop the feeder exists to unblock
    "deeplearning4j_tpu/datasets",
    # serving's HTTP ingress: request decode / response encode are the
    # pragma'd host boundaries; anything else must stay async
    "deeplearning4j_tpu/ui/serving_module.py",
    # the elastic straggler A/B: its only legitimate fetches are the
    # once-per-arm wall-clock readouts after fit() returns (pragma'd);
    # a per-round sync would hand the ASYNC arm the same barrier the
    # benchmark exists to show it avoiding
    "benchmarks/elastic.py",
    # the chaos worker's training loop: every host read is either the
    # watchdog-guarded per-step collective wait or a replicated-scalar
    # bookkeeping read after it (pragma'd) — an unguarded fetch is a
    # hang the watchdog cannot classify
    "tests/multihost_chaos_worker.py",
)

PRAGMA = "# host-sync-ok"

# pattern -> what it does on a device value
PATTERNS = (
    (re.compile(r"\bfloat\("), "float() blocks on a device value"),
    (re.compile(r"\bnp\.asarray\("),
     "np.asarray() copies device->host (jnp.asarray stays on device)"),
    (re.compile(r"\.item\(\)"), ".item() blocks on a device value"),
)


def iter_files(paths):
    for p in paths:
        path = Path(p)
        if not path.is_absolute():
            path = REPO_ROOT / path
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def check_file(path: Path):
    """Yield (lineno, line, reason) for each unallowed hit."""
    try:
        text = path.read_text(encoding="utf-8")
    except OSError as e:
        print(f"warning: cannot read {path}: {e}", file=sys.stderr)
        return
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if stripped.startswith("#"):        # comment-only line
            continue
        if PRAGMA in line:                  # explicit allowlist
            continue
        # ignore the trailing comment: a pattern named in prose
        # ("avoid float(x) here") is not a hit
        code = line.split("#", 1)[0] if '"#"' not in line \
            and "'#'" not in line else line
        for rx, reason in PATTERNS:
            if rx.search(code):
                yield lineno, line.rstrip(), reason
                break


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--paths", nargs="+", default=list(DEFAULT_PATHS),
                    help="files/directories to scan (default: the "
                         "jit hot paths)")
    args = ap.parse_args(argv)

    hits = []
    for path in iter_files(args.paths):
        for lineno, line, reason in check_file(path):
            hits.append((path, lineno, line, reason))

    if not hits:
        print("check_host_sync: clean "
              f"({', '.join(str(p) for p in args.paths)})")
        return 0
    print("check_host_sync: host-sync patterns in jit hot paths "
          f"({len(hits)} hit{'s' if len(hits) != 1 else ''}):\n",
          file=sys.stderr)
    for path, lineno, line, reason in hits:
        try:
            rel = path.relative_to(REPO_ROOT)
        except ValueError:
            rel = path
        print(f"  {rel}:{lineno}: {reason}\n    {line.strip()}",
              file=sys.stderr)
    print("\nIf the value is a trace-time Python constant (shape math, "
          "env var), annotate the line with\n"
          f"  `{PRAGMA}: <reason>`\n"
          "otherwise move the read out of the hot path (the telemetry "
          "ring buffer in observe/ exists for this).", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
