#!/usr/bin/env python
"""Static checker for host-sync patterns in jit-traced hot paths.

Back-compat CLI shim: the checker itself now lives in
``tools/graftlint`` as the ``host-sync`` rule (one of five — see
``python -m tools.graftlint --list-rules``). This entry point keeps the
historical interface working unchanged:

- ``python tools/check_host_sync.py`` checks the same default hot-path
  set (now ``tools.graftlint.rules.host_sync.HOT_PATHS``),
- ``--paths a.py dir/`` overrides it,
- ``# host-sync-ok`` pragmas keep suppressing (graftlint treats the
  pragma as an alias of ``# graftlint: disable=host-sync``),
- exit status 0 when clean, 1 when unallowed hits are found.

New code should prefer ``python -m tools.graftlint`` (runtests.sh
already does), which adds the donation-safety / recompile-hazard /
thread-discipline / tracer-leak rules and the baseline workflow.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

# runnable both as `python tools/check_host_sync.py` (script: repo root
# not on sys.path) and as `python -m tools.check_host_sync`
REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftlint.engine import scan                    # noqa: E402
from tools.graftlint.rules.host_sync import (              # noqa: E402
    HOT_PATHS, HostSyncRule, PATTERNS)

PRAGMA = "# host-sync-ok"
DEFAULT_PATHS = HOT_PATHS


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="host-sync patterns in jit hot paths "
                    "(shim over tools.graftlint)")
    ap.add_argument("--paths", nargs="+", default=list(DEFAULT_PATHS),
                    help="files/directories to scan (default: the "
                         "jit hot paths)")
    args = ap.parse_args(argv)

    # an explicit --paths set means "check exactly these", so the rule's
    # own hot-path scoping is overridden with the requested set
    rule = HostSyncRule(paths=args.paths)
    hits = scan(args.paths, rules=[rule])

    if not hits:
        print("check_host_sync: clean "
              f"({', '.join(str(p) for p in args.paths)})")
        return 0
    print("check_host_sync: host-sync patterns in jit hot paths "
          f"({len(hits)} hit{'s' if len(hits) != 1 else ''}):\n",
          file=sys.stderr)
    for f in hits:
        print(f"  {f.rel}:{f.line}: {f.message}\n    {f.snippet}",
              file=sys.stderr)
    print("\nIf the value is a trace-time Python constant (shape math, "
          "env var), annotate the line with\n"
          f"  `{PRAGMA}: <reason>`\n"
          "otherwise move the read out of the hot path (the telemetry "
          "ring buffer in observe/ exists for this).", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
