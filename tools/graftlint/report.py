"""Human, JSON and SARIF report rendering for graftlint findings.

SARIF (Static Analysis Results Interchange Format 2.1.0) is the
subset CI code-annotation surfaces consume: one run, the rule
catalog under ``tool.driver.rules``, one ``result`` per finding with
a physical location and the baseline fingerprint under
``partialFingerprints``. Baselined findings are emitted at level
``note`` (visible, non-blocking); new findings at ``error``."""

from __future__ import annotations

import json
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence

from tools.graftlint.engine import Finding
from tools.graftlint.baseline import fingerprints


def render_human(new: Sequence[Finding], baselined: Sequence[Finding],
                 stale: Sequence[str], n_files: int, seconds: float,
                 stream=None) -> None:
    stream = stream if stream is not None else sys.stderr
    for f in new:
        print(f"{f.rel}:{f.line}: [{f.rule}] {f.message}", file=stream)
        if f.snippet:
            print(f"    {f.snippet}", file=stream)
    by_rule = Counter(f.rule for f in new)
    parts = [f"{n} {r}" for r, n in sorted(by_rule.items())]
    status = "clean" if not new else \
        f"{len(new)} finding{'s' if len(new) != 1 else ''}" \
        + (f" ({', '.join(parts)})" if parts else "")
    extra = []
    if baselined:
        extra.append(f"{len(baselined)} baselined")
    if stale:
        extra.append(f"{len(stale)} stale baseline "
                     f"entr{'ies' if len(stale) != 1 else 'y'} "
                     "(re-run --write-baseline to prune)")
    suffix = f" [{'; '.join(extra)}]" if extra else ""
    print(f"graftlint: {status} — {n_files} files in {seconds:.2f}s"
          f"{suffix}", file=stream)
    if new:
        print(
            "\nSuppress a deliberate pattern with a line pragma\n"
            "  `# graftlint: disable=<rule>: <reason>`\n"
            "or triage it into the baseline with --write-baseline "
            "(tools/graftlint/README.md).", file=stream)


def render_json(new: Sequence[Finding], baselined: Sequence[Finding],
                stale: Sequence[str], n_files: int, seconds: float,
                stream=None) -> None:
    stream = stream if stream is not None else sys.stdout

    def rows(findings: Sequence[Finding], is_baselined: bool
             ) -> List[Dict]:
        fps = fingerprints(findings)
        return [{"rule": f.rule, "path": f.rel, "line": f.line,
                 "message": f.message, "snippet": f.snippet,
                 "fingerprint": fp, "baselined": is_baselined}
                for f, fp in zip(findings, fps)]

    doc = {
        "version": 1,
        "findings": rows(new, False) + rows(baselined, True),
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "stale_baseline_entries": len(stale),
            "files": n_files,
            "seconds": round(seconds, 3),
            "by_rule": dict(Counter(f.rule for f in new)),
        },
    }
    json.dump(doc, stream, indent=2, sort_keys=True)
    stream.write("\n")


SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(new: Sequence[Finding], baselined: Sequence[Finding],
                 stale: Sequence[str], n_files: int, seconds: float,
                 stream=None) -> None:
    stream = stream if stream is not None else sys.stdout
    from tools.graftlint.rules import ALL_RULES
    used = {f.rule for f in new} | {f.rule for f in baselined}
    rules_meta = [
        {"id": cls.name,
         "shortDescription": {"text": cls.description}}
        for cls in ALL_RULES if cls.name in used]
    # project-level findings (e.g. catalog parse errors) carry rule
    # names no registered class owns only if a rule is renamed —
    # keep the run valid anyway
    known = {cls.name for cls in ALL_RULES}
    for name in sorted(used - known):
        rules_meta.append({"id": name,
                           "shortDescription": {"text": name}})

    def results(findings: Sequence[Finding], level: str) -> List[Dict]:
        fps = fingerprints(findings)
        out = []
        for f, fp in zip(findings, fps):
            out.append({
                "ruleId": f.rule,
                "level": level,
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.rel.replace("\\", "/")},
                        "region": {"startLine": f.line},
                    },
                }],
                "partialFingerprints": {"graftlint/v1": fp},
            })
        return out

    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "tools/graftlint/README.md",
                "rules": rules_meta,
            }},
            "results": (results(new, "error")
                        + results(baselined, "note")),
            "properties": {
                "files": n_files,
                "seconds": round(seconds, 3),
                "staleBaselineEntries": len(stale),
            },
        }],
    }
    json.dump(doc, stream, indent=2, sort_keys=True)
    stream.write("\n")
