"""Human and JSON report rendering for graftlint findings."""

from __future__ import annotations

import json
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence

from tools.graftlint.engine import Finding
from tools.graftlint.baseline import fingerprints


def render_human(new: Sequence[Finding], baselined: Sequence[Finding],
                 stale: Sequence[str], n_files: int, seconds: float,
                 stream=None) -> None:
    stream = stream if stream is not None else sys.stderr
    for f in new:
        print(f"{f.rel}:{f.line}: [{f.rule}] {f.message}", file=stream)
        if f.snippet:
            print(f"    {f.snippet}", file=stream)
    by_rule = Counter(f.rule for f in new)
    parts = [f"{n} {r}" for r, n in sorted(by_rule.items())]
    status = "clean" if not new else \
        f"{len(new)} finding{'s' if len(new) != 1 else ''}" \
        + (f" ({', '.join(parts)})" if parts else "")
    extra = []
    if baselined:
        extra.append(f"{len(baselined)} baselined")
    if stale:
        extra.append(f"{len(stale)} stale baseline "
                     f"entr{'ies' if len(stale) != 1 else 'y'} "
                     "(re-run --write-baseline to prune)")
    suffix = f" [{'; '.join(extra)}]" if extra else ""
    print(f"graftlint: {status} — {n_files} files in {seconds:.2f}s"
          f"{suffix}", file=stream)
    if new:
        print(
            "\nSuppress a deliberate pattern with a line pragma\n"
            "  `# graftlint: disable=<rule>: <reason>`\n"
            "or triage it into the baseline with --write-baseline "
            "(tools/graftlint/README.md).", file=stream)


def render_json(new: Sequence[Finding], baselined: Sequence[Finding],
                stale: Sequence[str], n_files: int, seconds: float,
                stream=None) -> None:
    stream = stream if stream is not None else sys.stdout

    def rows(findings: Sequence[Finding], is_baselined: bool
             ) -> List[Dict]:
        fps = fingerprints(findings)
        return [{"rule": f.rule, "path": f.rel, "line": f.line,
                 "message": f.message, "snippet": f.snippet,
                 "fingerprint": fp, "baselined": is_baselined}
                for f, fp in zip(findings, fps)]

    doc = {
        "version": 1,
        "findings": rows(new, False) + rows(baselined, True),
        "summary": {
            "new": len(new),
            "baselined": len(baselined),
            "stale_baseline_entries": len(stale),
            "files": n_files,
            "seconds": round(seconds, 3),
            "by_rule": dict(Counter(f.rule for f in new)),
        },
    }
    json.dump(doc, stream, indent=2, sort_keys=True)
    stream.write("\n")
