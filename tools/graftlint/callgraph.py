"""Cross-module call graph over function summaries.

Resolution generalizes the import-table pattern donation-safety uses:

- a bare name resolves through the module's own functions, then its
  ``from x import f`` table;
- ``alias.f`` resolves when ``alias`` names an imported module;
- ``self.m`` resolves to the enclosing class's method, falling back
  to the project-wide method index;
- any other ``obj.m`` / ``a.b.m`` resolves by final attribute name
  against the method index (a deliberate may-alias over-approximation:
  good for reachability, so rules that need precision must check
  ``unambiguous()``).

``reaching(seeds)`` runs the cycle-safe fixed point: the set of
functions from which any seed is transitively callable. Monotone set
growth terminates on arbitrary recursion, mutual or otherwise.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Sequence, Set, Tuple

from tools.graftlint.summaries import FunctionSummary, ModuleSummary


class CallGraph:
    def __init__(self, modules: Dict[str, ModuleSummary]):
        self.modules = modules
        # flat qname key ("mod::Class.method") -> summary
        self.functions: Dict[str, FunctionSummary] = {}
        # method/function final name -> all qname keys defining it
        self.method_index: Dict[str, List[str]] = {}
        for ms in modules.values():
            for s in ms.functions.values():
                self.functions[s.key] = s
                final = s.qname.split(".")[-1]
                self.method_index.setdefault(final, []).append(s.key)
        for keys in self.method_index.values():
            keys.sort()
        self._cache: Dict[Tuple[str, str], Tuple[str, ...]] = {}

    # -- resolution ------------------------------------------------------

    def resolve(self, module: str, caller_qname: str,
                callee: str) -> Tuple[str, ...]:
        """Candidate summary keys for a dotted callee as written in
        ``module`` inside ``caller_qname``. Empty when unknown
        (builtins, third-party, dynamic)."""
        ck = (f"{module}::{caller_qname}", callee)
        hit = self._cache.get(ck)
        if hit is not None:
            return hit
        out = tuple(dict.fromkeys(
            self._resolve(module, caller_qname, callee)))
        self._cache[ck] = out
        return out

    def _resolve(self, module: str, caller_qname: str,
                 callee: str) -> List[str]:
        ms = self.modules.get(module)
        parts = callee.split(".")
        final = parts[-1]
        if ms is not None and len(parts) == 1:
            # module-local function (incl. sibling methods named
            # without self — rare) then from-imports
            local = f"{module}::{callee}"
            if local in self.functions:
                return [local]
            tgt = ms.imports.get(callee)
            if tgt is not None:
                key = self._dotted_to_key(tgt)
                if key is not None:
                    return [key]
            return []
        if parts[0] == "self" and len(parts) == 2:
            cls_prefix = caller_qname.rsplit(".", 1)[0] \
                if "." in caller_qname else ""
            if cls_prefix:
                key = f"{module}::{cls_prefix}.{final}"
                if key in self.functions:
                    return [key]
            return list(self.method_index.get(final, []))
        if ms is not None and parts[0] in ms.imports:
            # alias.f / alias.sub.f through an imported module
            tgt = ms.imports[parts[0]] + "." + ".".join(parts[1:])
            key = self._dotted_to_key(tgt)
            if key is not None:
                return [key]
        # fall back to the project-wide method index by final name
        return list(self.method_index.get(final, []))

    def _dotted_to_key(self, dotted: str) -> "str | None":
        """``pkg.mod.func`` or ``pkg.mod.Class.method`` -> summary key
        when some split into (module, qname) exists."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules:
                qname = ".".join(parts[i:])
                key = f"{mod}::{qname}"
                if key in self.functions:
                    return key
                return None
        return None

    def unambiguous(self, keys: Sequence[str]) -> bool:
        return len(keys) == 1

    # -- fixed point -----------------------------------------------------

    def reaching(self, seeds: Iterable[str]) -> Set[str]:
        """Keys of every function from which a seed is transitively
        reachable through resolvable calls (seeds included).

        Plain monotone worklist over the reverse graph — the set only
        grows, so mutual recursion and cycles terminate."""
        reach: Set[str] = {s for s in seeds if s in self.functions}
        # precompute forward edges once
        edges: Dict[str, Set[str]] = {}
        for key, s in self.functions.items():
            tgt: Set[str] = set()
            for cs in s.calls:
                tgt.update(self.resolve(s.module, s.qname, cs.callee))
            edges[key] = tgt
        rev: Dict[str, Set[str]] = {}
        for src, tgts in edges.items():
            for t in tgts:
                rev.setdefault(t, set()).add(src)
        work = list(reach)
        while work:
            cur = work.pop()
            for caller in rev.get(cur, ()):
                if caller not in reach:
                    reach.add(caller)
                    work.append(caller)
        return reach

    def seeds_matching(self, pred: Callable[[FunctionSummary], bool]
                       ) -> Set[str]:
        return {k for k, s in self.functions.items() if pred(s)}

    def reachable_from(self, seeds: Iterable[str]) -> Set[str]:
        """Forward closure: every function transitively callable from
        the seeds (seeds included). Same monotone worklist, forward
        edges."""
        reach: Set[str] = {s for s in seeds if s in self.functions}
        work = list(reach)
        while work:
            cur = work.pop()
            s = self.functions[cur]
            for cs in s.calls:
                for tgt in self.resolve(s.module, s.qname, cs.callee):
                    if tgt not in reach:
                        reach.add(tgt)
                        work.append(tgt)
        return reach
