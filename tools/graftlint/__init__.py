"""graftlint: JAX-aware static analysis for the tpu-dl4j codebase.

An AST-based, rule-pluggable analyzer that generalizes the old
``tools/check_host_sync.py`` grep into a framework. Five rules ship:

- ``host-sync``         hidden device->host syncs in jit hot paths
- ``donation-safety``   use-after-donate and numpy buffers reaching
                        ``donate_argnums`` parameters (the PR 1 bug)
- ``recompile-hazard``  jit construction in loops / per-call paths,
                        data-dependent static args, traced branching
- ``thread-discipline`` cross-thread attribute writes without a common
                        lock (the PR 4 / PR 6 bug), lock-order inversion
- ``tracer-leak``       traced values stored on self/globals/closures
                        from inside jitted functions

See tools/graftlint/README.md for the rule catalog, pragma syntax and
the baseline workflow. Entry point: ``python -m tools.graftlint``.
"""

from tools.graftlint.engine import (  # noqa: F401
    Finding, ModuleContext, Project, scan, REPO_ROOT)
from tools.graftlint.baseline import (  # noqa: F401
    fingerprint, load_baseline, write_baseline, split_baselined)
from tools.graftlint.rules import ALL_RULES, get_rules  # noqa: F401
