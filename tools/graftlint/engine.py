"""graftlint core: module contexts, pragma handling, the scan driver.

The engine owns everything rule-independent: walking the path set,
parsing each module once (source text, line table, AST), computing the
per-line pragma suppressions, running every rule's project-wide
``prepare`` pass (cross-module facts like "which imported names donate")
and then its per-module ``check`` pass, and filtering the findings
through the pragmas.

Pragma syntax (line-level, on the offending line)::

    x = float(loss)   # graftlint: disable=host-sync
    y = step(y, b)    # graftlint: disable=donation-safety,tracer-leak
    z = risky()       # graftlint: disable          (all rules)

``# host-sync-ok`` is a back-compat alias for
``# graftlint: disable=host-sync`` — every pragma the old
``check_host_sync.py`` tool accepted keeps working unchanged.
"""

from __future__ import annotations

import ast
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

# matches "# graftlint: disable=a,b" / "# graftlint:disable" anywhere in
# the line; the rule list is optional (absent = suppress every rule)
_PRAGMA_RX = re.compile(
    r"#\s*graftlint:\s*disable(?:\s*=\s*([\w\-, ]+))?")
_ALIAS_RX = re.compile(r"#\s*host-sync-ok")

ALL = "*"          # sentinel: every rule suppressed on this line


@dataclass(frozen=True)
class Finding:
    """One analyzer hit. ``line`` is 1-indexed; ``snippet`` is the
    stripped source line (also the baseline identity — see
    baseline.fingerprint)."""
    rule: str
    path: Path              # absolute
    line: int
    message: str
    snippet: str

    @property
    def rel(self) -> str:
        try:
            return str(self.path.relative_to(REPO_ROOT))
        except ValueError:
            return str(self.path)


class ModuleContext:
    """One parsed module: text, line table, AST, pragma map."""

    def __init__(self, path: Path, root: Path = REPO_ROOT):
        self.path = path
        self.root = root
        try:
            self.rel = str(path.relative_to(root))
        except ValueError:
            self.rel = str(path)
        self.text = path.read_text(encoding="utf-8")
        self._sha: Optional[str] = None
        self.lines: List[str] = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as e:
            self.parse_error = e
        self._disabled: Dict[int, Set[str]] = self._pragmas()

    def _pragmas(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            if "#" not in line:
                continue
            m = _PRAGMA_RX.search(line)
            if m:
                rules = m.group(1)
                if rules is None:
                    out.setdefault(i, set()).add(ALL)
                else:
                    out.setdefault(i, set()).update(
                        r.strip() for r in rules.split(",") if r.strip())
            if _ALIAS_RX.search(line):
                out.setdefault(i, set()).add("host-sync")
        return out

    @property
    def sha(self) -> str:
        if self._sha is None:
            from tools.graftlint.cache import sha_of
            self._sha = sha_of(self.text)
        return self._sha

    def suppressed(self, rule: str, line: int) -> bool:
        d = self._disabled.get(line)
        return bool(d) and (ALL in d or rule in d)

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, lineno: int, message: str) -> Finding:
        return Finding(rule=rule, path=self.path, line=lineno,
                       message=message, snippet=self.line_at(lineno))


class Project:
    """Cross-module facts shared between rules' prepare/check passes.

    ``modules`` maps repo-relative dotted module names
    (``deeplearning4j_tpu.nlp.skipgram``) to their contexts so rules can
    resolve imports; rules stash their own project-wide tables in
    ``facts[rule_name]``.

    The interprocedural layer lives here too: ``summaries`` (dotted
    module name -> ModuleSummary, see tools/graftlint/summaries.py)
    and ``callgraph`` (import-resolved, with the cycle-safe
    ``reaching`` fixed point). Pass a SummaryCache to skip re-analysis
    of files whose content hash is unchanged.
    """

    def __init__(self, contexts: Sequence[ModuleContext],
                 root: Path = REPO_ROOT, cache=None):
        from tools.graftlint.callgraph import CallGraph
        from tools.graftlint.summaries import build_module_summary
        self.root = root
        self.contexts = list(contexts)
        self.modules: Dict[str, ModuleContext] = {}
        for ctx in self.contexts:
            name = module_name_of(ctx.rel)
            if name:
                self.modules[name] = ctx
        self.facts: Dict[str, object] = {}
        self.summaries = {}
        for ctx in self.contexts:
            if ctx.tree is None:
                continue
            mod = module_name_of(ctx.rel) or ctx.rel
            ms = cache.get(ctx.rel, ctx.sha) if cache is not None \
                else None
            if ms is None:
                ms = build_module_summary(ctx.tree, ctx.text, mod,
                                          ctx.rel)
                if cache is not None:
                    cache.put(ctx.rel, ctx.sha, ms)
            self.summaries[mod] = ms
        self.callgraph = CallGraph(self.summaries)

    def context_for(self, path: Path) -> Optional[ModuleContext]:
        for ctx in self.contexts:
            if ctx.path == path:
                return ctx
        return None


def module_name_of(rel: str) -> Optional[str]:
    """``deeplearning4j_tpu/nlp/skipgram.py`` ->
    ``deeplearning4j_tpu.nlp.skipgram`` (packages keep their
    ``__init__`` suffix stripped)."""
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].replace("\\", "/").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts:
        return None
    return ".".join(parts)


def iter_files(paths: Iterable[str], root: Path = REPO_ROOT
               ) -> List[Path]:
    seen: Set[Path] = set()
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if not path.is_absolute():
            path = root / path
        if path.is_dir():
            found = sorted(q for q in path.rglob("*.py")
                           if "__pycache__" not in q.parts)
        elif path.suffix == ".py" and path.exists():
            found = [path]
        else:
            if not path.exists():
                print(f"graftlint: warning: no such path: {p}",
                      file=sys.stderr)
            found = []
        for f in found:
            if f not in seen:
                seen.add(f)
                out.append(f)
    return out


def scan(paths: Iterable[str], rules: Sequence = None,
         root: Path = REPO_ROOT, cache_path: Optional[Path] = None
         ) -> List[Finding]:
    """Run ``rules`` (default: every registered rule) over ``paths``;
    returns pragma-filtered findings sorted by (path, line, rule).

    ``cache_path`` (optional) enables the content-hash summary cache:
    unchanged files skip the interprocedural summarization pass and
    the cache is re-persisted after the scan."""
    from tools.graftlint.rules import get_rules
    if rules is None:
        rules = get_rules()
    contexts = []
    for f in iter_files(paths, root):
        try:
            contexts.append(ModuleContext(f, root))
        except OSError as e:
            print(f"graftlint: warning: cannot read {f}: {e}",
                  file=sys.stderr)
    cache = None
    if cache_path is not None:
        from tools.graftlint.cache import SummaryCache
        cache = SummaryCache(cache_path)
    project = Project(contexts, root, cache=cache)
    if cache is not None:
        cache.save()
    for rule in rules:
        prepare = getattr(rule, "prepare", None)
        if prepare is not None:
            prepare(project)
    findings: List[Finding] = []
    for ctx in contexts:
        for rule in rules:
            if not rule.applies(ctx):
                continue
            for f in rule.check(ctx, project):
                if not ctx.suppressed(f.rule, f.line):
                    findings.append(f)
    # rules may report findings that belong to the project rather than
    # any single module (e.g. metric-hygiene's catalog parse errors
    # against OBSERVABILITY.md); pragma filtering still applies when
    # the finding lands on a scanned module
    for rule in rules:
        hook = getattr(rule, "project_findings", None)
        if hook is None:
            continue
        for f in hook(project):
            ctx = project.context_for(f.path)
            if ctx is None or not ctx.suppressed(f.rule, f.line):
                findings.append(f)
    findings.sort(key=lambda f: (str(f.path), f.line, f.rule))
    return findings


class Rule:
    """Base class. ``name`` is the pragma / CLI identifier; ``paths``
    (optional) restricts the rule to repo-relative prefixes — rules
    without one run on every scanned file."""

    name = "base"
    description = ""
    paths: Optional[Sequence[str]] = None

    def applies(self, ctx: ModuleContext) -> bool:
        if self.paths is None:
            return True
        rel = ctx.rel.replace("\\", "/")
        if Path(rel).is_absolute() or ctx.root != REPO_ROOT:
            # outside the repo root (fixture corpora, ad-hoc scans —
            # whether reached by absolute path or a custom scan root):
            # path scoping is a repo-layout concept, run everywhere
            return True
        for p in self.paths:
            p = p.rstrip("/")
            if rel == p or rel.startswith(p + "/"):
                return True
        return False

    def prepare(self, project: Project) -> None:   # optional pre-pass
        pass

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        raise NotImplementedError


# ---- shared AST helpers (used by several rules) -------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.experimental.pjit.pjit`` -> that string; None for
    non-name/attribute chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_jit_callable(node: ast.AST, jit_aliases: Set[str]) -> bool:
    """True when ``node`` (a Call.func) names jax.jit / pjit (including
    ``from jax import jit`` aliases collected per-module)."""
    name = dotted_name(node)
    if name is None:
        return False
    if name in jit_aliases:
        return True
    return name in ("jax.jit", "jax.pjit", "pjit.pjit",
                    "jax.experimental.pjit.pjit")


def collect_jit_aliases(tree: ast.Module) -> Set[str]:
    """Names under which jax.jit/pjit are imported in this module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module == "jax" or node.module.endswith(".pjit"):
                for a in node.names:
                    if a.name in ("jit", "pjit"):
                        aliases.add(a.asname or a.name)
    return aliases


def literal_argnums(node: ast.AST) -> Optional[List[int]]:
    """Parse a literal donate_argnums/static_argnums value: int or
    tuple/list of ints. None when non-literal (conditional expressions,
    names) — callers must treat that as unknown, not empty."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) \
                    and isinstance(elt.value, int) \
                    and not isinstance(elt.value, bool):
                out.append(elt.value)
            else:
                return None
        return out
    return None
