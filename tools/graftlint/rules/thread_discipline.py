"""thread-discipline: cross-thread shared state without a common lock.

The PR 4 ``AsyncDataSetIterator`` reset race and the PR 6
``queue_depth`` accounting miss were both the same shape: a class
spawns a thread, and an instance attribute is mutated both by the
thread's code and by methods other threads call, with no lock (or no
*common* lock) covering every writer. Two findings:

- **unlocked-shared-write** — within a class that spawns threads
  (``threading.Thread(target=self.m ...)``, a nested closure handed to
  ``Thread``, or a ``threading.Thread`` subclass with ``run``), an
  instance attribute is written both from thread-side code (the target
  and everything it calls via ``self.*``) and from outside it, and the
  writers' held-lock sets share no common lock. ``__init__`` writes are
  pre-spawn and exempt.
- **lock-order-inversion** — two methods of one class acquire the same
  pair of ``self.*`` locks in opposite orders (``with self.a: with
  self.b:`` vs ``with self.b: with self.a:``): a classic ABBA deadlock.

Held locks are tracked through ``with self.<lock>:`` blocks where
``<lock>`` is an attribute assigned ``threading.Lock()/RLock()/
Condition()/Semaphore()`` in the class, or whose name contains
"lock"/"mutex"/"cond". Queue/Event primitives are internally
synchronized and excluded from the shared-write check.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.engine import (
    Finding, ModuleContext, Project, Rule, dotted_name)

RULE = "thread-discipline"

_LOCK_CTORS = {"threading.Lock", "threading.RLock",
               "threading.Condition", "threading.Semaphore",
               "threading.BoundedSemaphore", "Lock", "RLock",
               "Condition", "Semaphore", "BoundedSemaphore"}
# attributes whose values synchronize themselves — writes to the
# *binding* still race, but rebinding one is almost always init-shaped;
# mutating methods (q.put) aren't attribute writes anyway
_SELF_SYNC_CTORS = {"queue.Queue", "queue.SimpleQueue",
                    "queue.LifoQueue", "queue.PriorityQueue",
                    "threading.Event", "Queue", "SimpleQueue", "Event"}


def _is_thread_ctor(call: ast.Call) -> bool:
    return dotted_name(call.func) in ("threading.Thread", "Thread")


def _thread_target(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    if call.args:
        return None     # positional arg 0 is group, never the target
    return None


class _MethodScan(ast.NodeVisitor):
    """One method (or thread-closure) body: self.* writes with held
    locks, self-method calls, nested-with lock acquisition order."""

    def __init__(self, lock_attrs: Set[str]):
        self.lock_attrs = lock_attrs
        self.held: List[str] = []
        # attr -> list of (frozenset(held locks), lineno)
        self.writes: Dict[str, List[Tuple[frozenset, int]]] = {}
        self.calls: Set[str] = set()           # self.<m>() call targets
        self.pairs: List[Tuple[str, str, int]] = []  # (outer, inner, ln)
        self.spawns: List[ast.Call] = []       # Thread(...) ctor calls
        self.local_funcs: Dict[str, ast.AST] = {}

    def _lock_of(self, node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            name = node.attr
            if name in self.lock_attrs or any(
                    t in name.lower()
                    for t in ("lock", "mutex", "cond")):
                return name
        return None

    def visit_With(self, node: ast.With):
        acquired = []
        for item in node.items:
            self.visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                for outer in self.held:
                    if outer != lock:
                        self.pairs.append((outer, lock, node.lineno))
                self.held.append(lock)
                acquired.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for lock in acquired:
            self.held.remove(lock)

    visit_AsyncWith = visit_With

    def _record_write(self, target: ast.expr, lineno: int):
        if isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            self.writes.setdefault(target.attr, []).append(
                (frozenset(self.held), lineno))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write(elt, lineno)
        elif isinstance(target, ast.Starred):
            self._record_write(target.value, lineno)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        for t in node.targets:
            self._record_write(t, node.lineno)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        self._record_write(node.target, node.lineno)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self.visit(node.value)
        self._record_write(node.target, node.lineno)

    def visit_Call(self, node: ast.Call):
        if _is_thread_ctor(node):
            self.spawns.append(node)
        if isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            self.calls.add(node.func.attr)
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        # nested closure: scanned separately (it may be a thread target)
        self.local_funcs[node.name] = node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        pass


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, ctx: ModuleContext):
        self.node = node
        self.ctx = ctx
        self.methods: Dict[str, _MethodScan] = {}
        self.closure_scans: Dict[str, _MethodScan] = {}
        self.lock_attrs: Set[str] = set()
        self.self_sync_attrs: Set[str] = set()
        self.thread_entries: Set[str] = set()      # method names
        self.thread_closures: Set[str] = set()     # "method.closure"
        self.is_thread_subclass = any(
            dotted_name(b) in ("threading.Thread", "Thread")
            for b in node.bases)
        self._collect()

    def _collect(self):
        # pass 1: lock / self-synchronized attribute discovery
        for m in self.node.body:
            if not isinstance(m, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call):
                    ctor = dotted_name(sub.value.func)
                    for t in sub.targets:
                        if isinstance(t, ast.Attribute) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id == "self":
                            if ctor in _LOCK_CTORS:
                                self.lock_attrs.add(t.attr)
                            elif ctor in _SELF_SYNC_CTORS:
                                self.self_sync_attrs.add(t.attr)
        # pass 2: per-method scans
        for m in self.node.body:
            if not isinstance(m, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan(self.lock_attrs)
            for stmt in m.body:
                scan.visit(stmt)
            self.methods[m.name] = scan
            for name, fn in scan.local_funcs.items():
                sub = _MethodScan(self.lock_attrs)
                for stmt in fn.body:
                    sub.visit(stmt)
                self.closure_scans[f"{m.name}.{name}"] = sub
            # thread targets spawned by this method
            for spawn in scan.spawns:
                tgt = _thread_target(spawn)
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    self.thread_entries.add(tgt.attr)
                elif isinstance(tgt, ast.Name) \
                        and tgt.id in scan.local_funcs:
                    self.thread_closures.add(f"{m.name}.{tgt.id}")
        if self.is_thread_subclass and "run" in self.methods:
            self.thread_entries.add("run")

    def thread_side_methods(self) -> Set[str]:
        """Thread entries plus everything they reach via self.* calls
        (transitive, within the class)."""
        side = set(self.thread_entries)
        frontier = list(side)
        while frontier:
            m = frontier.pop()
            scan = self.methods.get(m)
            if scan is None:
                continue
            for callee in scan.calls:
                if callee in self.methods and callee not in side:
                    side.add(callee)
                    frontier.append(callee)
        return side

    def spawns_threads(self) -> bool:
        return bool(self.thread_entries or self.thread_closures)


class ThreadDisciplineRule(Rule):
    name = RULE
    description = ("instance attributes mutated across threads without "
                   "a common lock; inconsistent lock acquisition order")
    paths = ("deeplearning4j_tpu",)

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(node, ctx)
                yield from self._check_shared_writes(info)
                yield from self._check_lock_order(info)

    # ---- unlocked cross-thread writes ------------------------------------
    def _check_shared_writes(self, info: _ClassInfo
                             ) -> Iterable[Finding]:
        if not info.spawns_threads():
            return
        side = info.thread_side_methods()
        # writer table: attr -> [(method label, is_thread_side,
        #                         locks, line)]
        writers: Dict[str, List[Tuple[str, bool, frozenset, int]]] = {}

        def add(label: str, thread_side: bool, scan: _MethodScan):
            for attr, accesses in scan.writes.items():
                if attr in info.lock_attrs \
                        or attr in info.self_sync_attrs:
                    continue
                for locks, line in accesses:
                    writers.setdefault(attr, []).append(
                        (label, thread_side, locks, line))

        for name, scan in info.methods.items():
            if name in ("__init__", "__new__"):
                continue          # pre-spawn construction
            add(name, name in side, scan)
        for label, scan in info.closure_scans.items():
            add(label, label in info.thread_closures, scan)

        for attr, ws in sorted(writers.items()):
            t_side = [w for w in ws if w[1]]
            o_side = [w for w in ws if not w[1]]
            if not t_side or not o_side:
                continue
            methods_t = sorted({w[0] for w in t_side})
            methods_o = sorted({w[0] for w in o_side})
            common = frozenset.intersection(
                *[w[2] for w in ws]) if ws else frozenset()
            if common:
                continue
            # flag every unlocked write site (locked-but-disjoint sites
            # are flagged too: they prove no common lock exists)
            flagged = [w for w in ws if not w[2]] or ws
            for label, _ts, _locks, line in flagged:
                yield info.ctx.finding(
                    RULE, line,
                    f"'self.{attr}' is written from thread-side "
                    f"{methods_t} and from {methods_o} with no common "
                    f"lock held (class {info.node.name} spawns "
                    "threads) — guard every writer with one lock or "
                    "make the state thread-local")

    # ---- lock ordering ---------------------------------------------------
    def _check_lock_order(self, info: _ClassInfo) -> Iterable[Finding]:
        order: Dict[Tuple[str, str], Tuple[str, int]] = {}
        scans = dict(info.methods)
        scans.update(info.closure_scans)
        for label, scan in sorted(scans.items()):
            for outer, inner, line in scan.pairs:
                order.setdefault((outer, inner), (label, line))
        for (a, b), (label, line) in sorted(order.items()):
            rev = order.get((b, a))
            if rev is not None and (a, b) < (b, a):
                yield info.ctx.finding(
                    RULE, line,
                    f"lock-order inversion in class {info.node.name}: "
                    f"'{label}' acquires self.{a} then self.{b}, but "
                    f"'{rev[0]}' (line {rev[1]}) acquires them in the "
                    "opposite order — a concurrent pair can deadlock "
                    "(ABBA)")
