"""release-discipline: what you acquire, you release — on every path.

The PR 11 inflight-accounting bug: ``RemoteDispatcher`` incremented a
node's inflight counter, the transport raised, the retry loop
incremented the *next* node — and the first node's count never came
back down, so least-loaded routing starved it forever. The fix moved
the decrement into a ``finally`` that runs before any retry's
increment; this rule machine-checks that shape.

Tracked resources (from the summary layer's CFG-lite pass):

- bare ``.acquire()`` on any receiver (locks/semaphores outside
  ``with``);
- attribute-based counter increments whose name is capacity-shaped
  (``inflight``/``pending``/``active``/``slot``/``claim``/...) —
  ``self._inflight[nid] = self._inflight.get(nid, 0) + 1`` and
  friends. Function-local tallies are ignored; they die with the
  frame.

Findings, anchored at the acquire site so one pragma covers the
resource:

- **unreleased path** — some CFG path (an exception edge past the
  acquire with no covering ``finally``/catch-all, or a plain
  return/fall-through) leaves the resource held;
- **re-acquire before release** — a loop's next iteration acquires
  the same resource while the previous hold is still live: the retry
  invariant at parallel/remote.py's ``_send``.

Resources handed off by design (acquired in ``submit``, released by a
completion callback) are cross-function and must carry a pragma
saying who releases them — that is the documentation, not noise.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

from tools.graftlint.engine import (Finding, ModuleContext, Project,
                                    Rule, module_name_of)

_KIND_TEXT = {"exception": "an exception edge",
              "exit": "a return/fall-through path"}


class ReleaseDisciplineRule(Rule):
    name = "release-discipline"
    description = ("acquired locks/semaphores and inflight-counter "
                   "increments must be released on every CFG path "
                   "(including exceptions), and released before any "
                   "loop re-acquire")

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        mod = module_name_of(ctx.rel) or ctx.rel
        ms = project.summaries.get(mod)
        if ms is None:
            return
        for s in ms.functions.values():
            grouped: Dict[Tuple[str, int], List[str]] = {}
            for ri in s.resource_issues:
                if ri.kind == "reacquire":
                    yield ctx.finding(
                        self.name, ri.lineno,
                        f"{s.qname} re-acquires {ri.key} (held since "
                        f"line {ri.acquire_lineno}) before releasing "
                        f"it — release in a finally before the next "
                        f"attempt, like RemoteDispatcher._send")
                else:
                    grouped.setdefault(
                        (ri.key, ri.acquire_lineno), []).append(ri.kind)
            for (key, acq), kinds in sorted(grouped.items()):
                paths = " and ".join(
                    _KIND_TEXT[k] for k in sorted(set(kinds)))
                yield ctx.finding(
                    self.name, acq,
                    f"{s.qname} acquires {key} here but {paths} "
                    f"leaves it held — release in a finally, or "
                    f"pragma this line naming who releases it")
