"""Rule registry. Adding a rule = write a module exposing a Rule
subclass and list it here; the CLI, pragma parser and baseline pick it
up automatically."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from tools.graftlint.rules.host_sync import HostSyncRule
from tools.graftlint.rules.chaos_hygiene import ChaosHygieneRule
from tools.graftlint.rules.donation_safety import DonationSafetyRule
from tools.graftlint.rules.recompile_hazard import RecompileHazardRule
from tools.graftlint.rules.thread_discipline import ThreadDisciplineRule
from tools.graftlint.rules.tracer_leak import TracerLeakRule
from tools.graftlint.rules.deadline_propagation import \
    DeadlinePropagationRule
from tools.graftlint.rules.release_discipline import \
    ReleaseDisciplineRule
from tools.graftlint.rules.atomic_write import AtomicWriteRule
from tools.graftlint.rules.metric_hygiene import MetricHygieneRule

ALL_RULES = (HostSyncRule, ChaosHygieneRule, DonationSafetyRule,
             RecompileHazardRule, ThreadDisciplineRule, TracerLeakRule,
             DeadlinePropagationRule, ReleaseDisciplineRule,
             AtomicWriteRule, MetricHygieneRule)

RULES_BY_NAME: Dict[str, type] = {r.name: r for r in ALL_RULES}


def get_rules(names: Optional[Sequence[str]] = None) -> List:
    """Instantiate the named rules (default: all), preserving registry
    order; unknown names raise with the valid set."""
    if names is None:
        return [cls() for cls in ALL_RULES]
    out = []
    for n in names:
        cls = RULES_BY_NAME.get(n)
        if cls is None:
            raise ValueError(
                f"unknown rule {n!r}; available: "
                f"{', '.join(sorted(RULES_BY_NAME))}")
        out.append(cls())
    return out
