"""deadline-propagation: the deadline kwarg must survive the whole
ingress -> dispatch chain.

PR 14 threaded an end-to-end ``Deadline`` from the ui ingress
(``X-Deadline-Ms`` / ``deadline_ms``) through admission, batching and
remote dispatch — and the very first ui module draft dropped it one
hop in, so every tier below ran with no budget. The invariant is
cross-module by construction, which is exactly what the summary layer
exists for:

- **seams** are the dispatch methods (``RemoteDispatcher.predict``,
  ``ServingEngine.submit``, ...); the cycle-safe fixed point marks
  every function that transitively reaches one;
- **ingress** is any function defined in a ``ui`` package; the
  forward closure from those marks the serving path;
- on the intersection, any function holding a deadline (the
  ``deadline`` parameter or a local bound from a ``Deadline``
  constructor) must hand it to each seam-reaching callee at at least
  one call site — as ``deadline=``, positionally, through ``**kw``,
  or via any argument derived from it (a capped timeout counts).

The "at least one site" form deliberately admits the duck-typing
idiom ``f(x, deadline=d) if d is not None else f(x)``. A callee that
reaches a seam but cannot carry a deadline at all (no ``deadline``
parameter, no ``**kwargs``) is reported too when resolution is
unambiguous — that hole cannot be fixed at the call site.
"""

from __future__ import annotations

from typing import Dict, Iterable, Set

from tools.graftlint.engine import (Finding, ModuleContext, Project,
                                    Rule, module_name_of)

# dispatch seams: qname ("Class.method") exact matches
SEAM_QNAMES = frozenset({
    "RemoteDispatcher.predict", "RemoteDispatcher.output",
    "RemoteDispatcher.send", "RemoteDispatcher._send",
    "ServingEngine.submit", "ServingEngine.output",
    "GenerationEngine.submit", "GenerationEngine.generate",
    "FleetRouter.submit", "FleetRouter.output", "FleetRouter.generate",
    "ModelPool.submit", "GenerationPool.submit",
})


def _is_ingress(summary) -> bool:
    return "ui" in summary.module.split(".")


class DeadlinePropagationRule(Rule):
    name = "deadline-propagation"
    description = ("a deadline in scope on the ui ingress -> dispatch "
                   "path must be forwarded to every seam-reaching "
                   "callee (kwarg, **kw, or a timeout derived from it)")

    def prepare(self, project: Project) -> None:
        cg = project.callgraph
        seams = cg.seeds_matching(lambda s: s.qname in SEAM_QNAMES)
        seam_reaching = cg.reaching(seams)
        ingress = cg.seeds_matching(_is_ingress)
        on_path = cg.reachable_from(ingress) & seam_reaching
        project.facts[self.name] = {
            "seam_reaching": seam_reaching, "on_path": on_path}

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        facts = project.facts.get(self.name)
        if not facts or ctx.tree is None:
            return
        mod = module_name_of(ctx.rel) or ctx.rel
        ms = project.summaries.get(mod)
        if ms is None:
            return
        cg = project.callgraph
        seam_reaching: Set[str] = facts["seam_reaching"]
        on_path: Set[str] = facts["on_path"]
        for s in ms.functions.values():
            if s.key not in on_path or not s.has_deadline:
                continue
            groups: Dict[str, list] = {}
            for cs in s.calls:
                groups.setdefault(cs.callee, []).append(cs)
            for callee, sites in sorted(groups.items()):
                cands = [c for c in cg.resolve(mod, s.qname, callee)
                         if c in seam_reaching and c != s.key]
                if not cands:
                    continue
                if any(cs.passes_deadline or cs.has_star_kw
                       for cs in sites):
                    continue
                accepts = any(
                    "deadline" in cg.functions[c].params
                    or cg.functions[c].has_varkw for c in cands)
                first = min(cs.lineno for cs in sites)
                if accepts:
                    yield ctx.finding(
                        self.name, first,
                        f"{s.qname} holds a deadline (line "
                        f"{s.deadline_lineno}) but calls "
                        f"{callee}() without it; the dispatch chain "
                        f"below loses its budget — pass deadline= "
                        f"(or derive the timeout from it)")
                elif cg.unambiguous(cands):
                    tgt = cg.functions[cands[0]]
                    yield ctx.finding(
                        self.name, first,
                        f"{s.qname} holds a deadline but "
                        f"{callee}() ({tgt.qname}) reaches a dispatch "
                        f"seam and cannot carry one (no deadline "
                        f"parameter, no **kwargs) — the budget stops "
                        f"propagating here")
