"""host-sync: hidden device->host transfers in jit hot paths.

The direct port of ``tools/check_host_sync.py`` (PR 2). ``float(x)``,
``np.asarray(x)`` and ``x.item()`` on a traced jax value force a
device->host sync (inside a trace, a ConcretizationTypeError at best;
on the dispatch path, a per-step stall at worst). The telemetry design
(observe/) exists so the train loop does exactly ONE device fetch per
flush interval; a stray ``float(loss)`` in ops/ or the solver undoes
that.

Scope: only the jit hot paths listed in ``HOT_PATHS`` — host-side code
is allowed (expected!) to touch host values. Trace-time Python
constants (shape math, env vars) are legitimate inside the hot paths
too: annotate them with ``# host-sync-ok: <reason>`` (the historical
pragma, kept as an alias of ``# graftlint: disable=host-sync``).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Iterable

from tools.graftlint.engine import Finding, ModuleContext, Project, Rule

# hot paths: everything here runs inside (or builds/dispatches) jitted
# step functions, where a hidden sync is a per-iteration cost. This is
# the accumulated PR 2..7 list from check_host_sync.py, unchanged.
HOT_PATHS = (
    "deeplearning4j_tpu/ops",
    "deeplearning4j_tpu/optimize/solver.py",
    "deeplearning4j_tpu/models",
    # parallel/ includes the serving engine, the fleet router, the
    # persisted AOT cache AND the cluster tier (node.py's registry
    # gossip / drain loop, remote.py's dispatch + breakers): the only
    # legitimate fetches are the completion-thread block/asarray pair,
    # the cache's one-time startup weights fingerprint, and the cluster
    # tier's host-side config/HTTP scalars (each pragma'd in place)
    "deeplearning4j_tpu/parallel",
    # the input-feeder hot path: a stray per-batch host sync here would
    # serialize ETL back onto the step loop the feeder exists to unblock
    "deeplearning4j_tpu/datasets",
    # serving's HTTP ingress: request decode / response encode are the
    # pragma'd host boundaries; anything else must stay async
    "deeplearning4j_tpu/ui/serving_module.py",
    # the elastic straggler A/B: only the once-per-arm wall-clock
    # readouts after fit() returns are legitimate (pragma'd)
    "benchmarks/elastic.py",
    # the chaos worker's training loop: every host read is either the
    # watchdog-guarded collective wait or a replicated-scalar
    # bookkeeping read after it (pragma'd)
    "tests/multihost_chaos_worker.py",
    # online learning rides both hot paths (the learner's fit loop,
    # the fleet's dispatch): the only legitimate host reads are the
    # between-steps snapshot copies, the stream serde boundary and the
    # scoring result fetch (pragma'd at each site)
    "deeplearning4j_tpu/online",
    # the decode tick loop: the (h, c) carry and PRNG state must stay
    # device-resident across ticks — the only legitimate fetches are
    # the sampled-tokens egress (the streamed payload itself), the
    # pre-traffic warmup sweep, and the init-time int8 calibration
    # probe (each pragma'd in place)
    "deeplearning4j_tpu/generation",
    # its HTTP ingress: SSE serialization is a host boundary like the
    # predict module's request decode
    "deeplearning4j_tpu/ui/generation_module.py",
    # the embedding producers feed the device pair stream: any host
    # sync here stalls pair generation, the measured bound the fused
    # native pairgen exists to raise. Legitimate reads (the lr-anneal
    # scalars, static vocab precomputes, telemetry counts) are pragma'd
    # in place.
    "deeplearning4j_tpu/nlp/sequence_vectors.py",
    "deeplearning4j_tpu/nlp/word2vec.py",
    "deeplearning4j_tpu/nlp/paragraph_vectors.py",
    "deeplearning4j_tpu/nlp/pairgen.py",
    # the ctypes loader runs host-side by definition, but sits on the
    # producer path — keep it clean of accidental device fetches
    "deeplearning4j_tpu/utils/native.py",
    # the retrieval query path: the fused kernel's whole point is that
    # only (k ids, k distances) cross the host boundary per query. The
    # legitimate fetches are exactly the per-shard top-k egress into
    # the host k-way merge, the int8 refine rescore (host f32 rows by
    # design), warmup/build-time index preparation, and the
    # scatter-gather JSON serde — each pragma'd in place. A stray
    # asarray on the distance matrix would silently reintroduce the
    # O(n_corpus) transfer the tier exists to kill.
    "deeplearning4j_tpu/retrieval",
    # its HTTP ingress, same contract as the predict/generate modules:
    # request decode / response encode are the pragma'd boundaries
    "deeplearning4j_tpu/ui/neighbors_module.py",
    # the legacy VPTree surface is host-side math by definition, but
    # server.py now fronts the jitted engine — police the shim so the
    # legacy contract can't quietly pull full distance rows back, and
    # keep the host trees (vptree/kdtree/lsh/kmeans/sptree) clean of
    # accidental device round-trips
    "deeplearning4j_tpu/clustering",
    # the tuned-config resolution path runs inside every consumer's
    # constructor AND fit's per-call setup: a stray device fetch here
    # would tax every engine start and every fit entry. The module is
    # json/hashlib bookkeeping by design — the only legitimate host
    # reads are the fingerprint's one-time weights digest (delegated to
    # aot_cache's pragma'd site) and save/load file IO.
    "deeplearning4j_tpu/optimize/autotune.py",
)

PATTERNS = (
    (re.compile(r"\bfloat\("), "float() blocks on a device value"),
    (re.compile(r"\bnp\.asarray\("),
     "np.asarray() copies device->host (jnp.asarray stays on device)"),
    (re.compile(r"\.item\(\)"), ".item() blocks on a device value"),
)


class HostSyncRule(Rule):
    name = "host-sync"
    description = ("device->host sync patterns (float()/np.asarray()/"
                   ".item()) in jit hot paths")
    paths = HOT_PATHS

    def __init__(self, paths=None):
        # the back-compat CLI shim passes an explicit path set; the
        # default is the curated hot-path list. Absolute entries under
        # the repo root are normalized so they match the repo-relative
        # module contexts.
        if paths is not None:
            from tools.graftlint.engine import REPO_ROOT
            norm = []
            for p in paths:
                pp = Path(p)
                if pp.is_absolute():
                    try:
                        p = str(pp.resolve().relative_to(REPO_ROOT))
                    except ValueError:
                        p = str(pp)
                norm.append(str(p))
            self.paths = tuple(norm)

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        for lineno, line in enumerate(ctx.lines, 1):
            stripped = line.strip()
            if stripped.startswith("#"):         # comment-only line
                continue
            # ignore the trailing comment: a pattern named in prose
            # ("avoid float(x) here") is not a hit
            code = line.split("#", 1)[0] if '"#"' not in line \
                and "'#'" not in line else line
            for rx, reason in PATTERNS:
                if rx.search(code):
                    yield ctx.finding(self.name, lineno, reason)
                    break
