"""atomic-write: shared-store files land whole or not at all.

The gossiped node registry, the AOT artifact store and the streaming
corpus shards are all plain files read concurrently by other
processes. The repo-wide protocol (parallel/node.py ``write``,
parallel/aot_cache.py manifest save, datasets/corpus.py shards) is:
write a ``tmp`` sibling in the same directory, then ``os.replace`` it
into place — rename is atomic on POSIX, so a reader sees the old
bytes or the new bytes, never a torn half-record. PR 14's fault
injection made the torn-write fault class reproducible; this rule
makes it unrepresentable in the shared-path modules.

A write counts as protocol-conformant when its destination is the tmp
half: bound from ``tempfile.*``, or an identifier/literal containing
``tmp``. Any other ``open(p, "w")`` / ``Path.write_text`` /
``Path.write_bytes`` in a scoped module is a finding. Deliberate
direct writes (e.g. the AOT blob body, which is checksummed and only
becomes visible through the manifest's atomic replace) carry a pragma
explaining their safety argument.
"""

from __future__ import annotations

from typing import Iterable

from tools.graftlint.engine import (Finding, ModuleContext, Project,
                                    Rule, module_name_of)


class AtomicWriteRule(Rule):
    name = "atomic-write"
    description = ("writes under gossip/registry/artifact-store paths "
                   "must use the tmp + os.replace protocol; a direct "
                   "write to a shared path is a torn-write hazard")
    # the modules whose files other processes read concurrently
    paths = (
        "deeplearning4j_tpu/parallel/node.py",
        "deeplearning4j_tpu/parallel/cluster.py",
        "deeplearning4j_tpu/parallel/aot_cache.py",
        "deeplearning4j_tpu/parallel/checkpoint.py",
        "deeplearning4j_tpu/datasets/corpus.py",
    )

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        mod = module_name_of(ctx.rel) or ctx.rel
        ms = project.summaries.get(mod)
        if ms is None:
            return
        for s in ms.functions.values():
            for w in s.writes:
                if w.tmp_like:
                    continue
                yield ctx.finding(
                    self.name, w.lineno,
                    f"{s.qname} writes {w.target!r} directly (via "
                    f"{w.via}) on a shared path — a concurrent reader "
                    f"can see a torn record; write a tmp sibling and "
                    f"os.replace() it into place")
