"""donation-safety: the PR 1 bug class, statically.

``donate_argnums`` hands a buffer to XLA: after the donating call the
caller's binding is invalid (jax raises on *device* reuse — but a
donated *numpy* buffer adopted zero-copy by the CPU backend is freed
out from under live device state: a silent use-after-free, the exact
PR 1 ``test_nlp_cluster`` NaN). Two findings:

- **numpy-into-donated** — a numpy-backed value (``np.asarray``/
  ``np.array``/any ``np.*`` constructor, ``.numpy()``) reaches a
  donated parameter position without a defensive ``jnp.array``/
  ``jnp.asarray``/``jax.device_put`` copy.
- **use-after-donate** — a binding passed at a donated position is
  read again after the donating call without being rebound; the loop
  body is analyzed twice so ``for b in it: loss = step(state, b)``
  (state never rebound) is caught as a loop-carried use.

Donating callables are recognized across modules: module-level
``@partial(jax.jit, donate_argnums=...)`` decorations, ``name =
jax.jit(fn, donate_argnums=...)`` / ``partial(jax.jit, ...)(fn)``
assignments, ``from x import donating_fn`` / ``import x as y`` +
``y.donating_fn`` call sites, plus the repo's train-step makers
(``make_train_step``/``make_scan_train_step``/``build_train_step``,
which donate arg 0 unless called with ``donate=False``). The inference
builders (``build_inference_fn`` — plain or quantized — and
``quantize_model``) are pinned as NON-donating: serving replays
committed buffers across requests, so the maker heuristic must never
claim them. Non-literal ``donate_argnums`` expressions are treated as
unknown (no finding) — we only flag what we can prove.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.engine import (
    Finding, ModuleContext, Project, Rule, collect_jit_aliases,
    dotted_name, is_jit_callable, literal_argnums, module_name_of)

RULE = "donation-safety"

# repo convention: the solver's step factories return a jitted step
# donating its TrainState (arg 0) unless built with donate=False
_MAKER_RX = re.compile(
    r"(?:^|\.)(?:make_(?:scan_)?train_step|_?build_(?:scan_)?train_step)$")

# the OTHER repo convention, pinned explicitly: inference builders
# (``model.build_inference_fn``, ``QuantizedModel.build_inference_fn``,
# ``quantize_model``) return callables that donate NOTHING — the serving
# engine replays committed params (and, quantized, int8 weight buffers
# adopted zero-copy from numpy) across every request, so donation there
# would be the PR 1 use-after-free all over again. Matching names are
# excluded from the maker heuristic no matter how it grows.
_NON_DONATING_RX = re.compile(
    r"(?:^|\.)(?:build_inference_fn|quantize_model)$")

_NUMPY_MODULES = ("np", "numpy", "onp")
# jnp/jax wrappers that take ownership with a device copy
_CLEANSERS = {"jnp.array", "jnp.asarray", "jnp.copy", "jax.device_put",
              "jax.numpy.array", "jax.numpy.asarray"}


def _is_partial(node: ast.AST) -> bool:
    return dotted_name(node) in ("functools.partial", "partial")


def _donating_positions(call: ast.Call,
                        jit_aliases: Set[str]) -> Optional[List[int]]:
    """Positions donated by the callable this Call builds, or None."""
    # jax.jit(fn, donate_argnums=...)
    if is_jit_callable(call.func, jit_aliases):
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return literal_argnums(kw.value)
        return None
    # functools.partial(jax.jit, donate_argnums=...)  (decorator or
    # applied immediately to a function)
    if _is_partial(call.func) and call.args \
            and is_jit_callable(call.args[0], jit_aliases):
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                return literal_argnums(kw.value)
    return None


def _maker_positions(call: ast.Call) -> Optional[List[int]]:
    """Train-step factory convention: donates arg 0 unless
    donate=False is passed explicitly."""
    name = dotted_name(call.func)
    if name is None or _NON_DONATING_RX.search(name) \
            or not _MAKER_RX.search(name):
        return None
    for kw in call.keywords:
        if kw.arg == "donate":
            if isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return None
            if not (isinstance(kw.value, ast.Constant)
                    and kw.value.value is True):
                return None        # donate=<expr>: unknown
    return [0]


def module_donators(ctx: ModuleContext) -> Dict[str, List[int]]:
    """Module-level names in ``ctx`` that donate, -> positions."""
    out: Dict[str, List[int]] = {}
    if ctx.tree is None:
        return out
    aliases = collect_jit_aliases(ctx.tree)
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _donating_positions(dec, aliases)
                    if pos:
                        out[node.name] = pos
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            pos = _assigned_donation(node.value, aliases)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = pos
    return out


def _assigned_donation(call: ast.Call,
                       aliases: Set[str]) -> Optional[List[int]]:
    pos = _donating_positions(call, aliases)
    if pos:
        return pos
    # partial(jax.jit, donate_argnums=...)(fn): outer call over a
    # donation-building inner call
    if isinstance(call.func, ast.Call):
        return _donating_positions(call.func, aliases)
    return None


def _is_numpy_call(node: ast.AST) -> bool:
    """A call that yields a host numpy array: np.<anything>(...) or
    x.numpy()."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is not None:
        head = name.split(".", 1)[0]
        if head in _NUMPY_MODULES and "." in name:
            return name not in _CLEANSERS
    if isinstance(node.func, ast.Attribute) and node.func.attr == "numpy":
        return True
    return False


def _is_cleansing_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) \
        and dotted_name(node.func) in _CLEANSERS


class _Scope:
    """Mutable dataflow state for one linear pass."""

    def __init__(self):
        self.tainted: Set[str] = set()      # numpy-backed bindings
        self.dead: Dict[str, int] = {}      # donated binding -> line

    def copy(self) -> "_Scope":
        s = _Scope()
        s.tainted = set(self.tainted)
        s.dead = dict(self.dead)
        return s

    def merge_branches(self, a: "_Scope", b: "_Scope"):
        # dead only when dead on every path (no false positives from
        # "the other branch donated"); tainted on any path
        self.tainted = a.tainted | b.tainted
        self.dead = {k: v for k, v in a.dead.items() if k in b.dead}


class _FunctionAnalyzer:
    """Linear abstract interpretation of one function (or the module
    top level). Loop bodies run twice so loop-carried donations — the
    ``for b: loss = step(state, b)`` shape — surface on the second
    pass."""

    def __init__(self, rule: "DonationSafetyRule", ctx: ModuleContext,
                 donators: Dict[str, List[int]],
                 jit_aliases: Set[str]):
        self.rule = rule
        self.ctx = ctx
        self.donators = dict(donators)      # callable name -> positions
        self.jit_aliases = jit_aliases
        self.scope = _Scope()
        self.findings: Dict[Tuple[int, str, str], Finding] = {}

    # ---- reporting -------------------------------------------------------
    def _report(self, line: int, kind: str, name: str, message: str):
        key = (line, kind, name)
        if key not in self.findings:
            self.findings[key] = self.ctx.finding(RULE, line, message)

    # ---- expression walk -------------------------------------------------
    def visit_expr(self, node: Optional[ast.AST]):
        """Detect loads of dead names and donating calls, inside out."""
        if node is None:
            return
        for child in ast.walk(node):
            if isinstance(child, ast.Name) \
                    and isinstance(child.ctx, ast.Load) \
                    and child.id in self.scope.dead:
                donated_at = self.scope.dead.pop(child.id)
                self._report(
                    child.lineno, "use-after-donate", child.id,
                    f"'{child.id}' was donated at line {donated_at} "
                    "(donate_argnums) and is read again here; its "
                    "buffer belongs to XLA now — rebind the result "
                    "or drop donation")
        # donating calls processed after their argument loads (the
        # donating call itself may legally read the binding)
        for child in ast.walk(node):
            if isinstance(child, ast.Call):
                self._handle_call(child)

    def _handle_call(self, call: ast.Call):
        positions = self._callee_positions(call)
        if positions is None:
            return
        for p in positions:
            if p >= len(call.args):
                continue
            arg = call.args[p]
            if isinstance(arg, ast.Name):
                if arg.id in self.scope.tainted:
                    self._report(
                        call.lineno, "numpy-into-donated", arg.id,
                        f"numpy-backed '{arg.id}' reaches donated "
                        f"parameter {p} of "
                        f"'{dotted_name(call.func) or '<call>'}'; the "
                        "CPU backend zero-copy adopts numpy buffers, "
                        "so donation frees host memory still in use — "
                        "copy with jnp.array(...) first")
                self.scope.dead[arg.id] = call.lineno
            elif _is_numpy_call(arg) and not _is_cleansing_call(arg):
                self._report(
                    call.lineno, "numpy-into-donated",
                    dotted_name(arg.func) or "<numpy temp>",
                    f"numpy temp from "
                    f"'{dotted_name(arg.func) or 'np call'}' flows "
                    f"straight into donated parameter {p} of "
                    f"'{dotted_name(call.func) or '<call>'}' — wrap "
                    "it in jnp.array(...) so the donated buffer is "
                    "device-owned")

    def _callee_positions(self, call: ast.Call) -> Optional[List[int]]:
        name = dotted_name(call.func)
        if name is not None and name in self.donators:
            return self.donators[name]
        # immediately-invoked donating jit: jax.jit(f, donate...)(args)
        if isinstance(call.func, ast.Call):
            return _donating_positions(call.func, self.jit_aliases)
        return None

    # ---- statement walk --------------------------------------------------
    def _bind(self, target: ast.AST, value: Optional[ast.AST]):
        """Assignment target: revive donated names, track numpy taint."""
        if isinstance(target, ast.Name):
            self.scope.dead.pop(target.id, None)
            if value is not None and _is_numpy_call(value):
                self.scope.tainted.add(target.id)
            elif value is not None and isinstance(value, ast.Name) \
                    and value.id in self.scope.tainted:
                self.scope.tainted.add(target.id)
            else:
                self.scope.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, None)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, None)
        # attribute/subscript targets: no binding tracked

    def run_body(self, body: List[ast.stmt]):
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self.visit_expr(getattr(stmt, "value", None))
            # locally-built donating callables become known callees
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                pos = _assigned_donation(stmt.value, self.jit_aliases) \
                    or _maker_positions(stmt.value)
                if pos:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.donators[t.id] = pos
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._bind(t, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) \
                    and stmt.target is not None:
                self._bind(stmt.target, stmt.value)
            else:                                     # AugAssign
                self.visit_expr(stmt.target)
                self._bind(stmt.target, None)
        elif isinstance(stmt, ast.Expr):
            self.visit_expr(stmt.value)
        elif isinstance(stmt, ast.Return):
            self.visit_expr(stmt.value)
        elif isinstance(stmt, ast.If):
            self.visit_expr(stmt.test)
            before = self.scope.copy()
            self.run_body(stmt.body)
            after_body = self.scope
            self.scope = before.copy()
            self.run_body(stmt.orelse)
            merged = _Scope()
            merged.merge_branches(after_body, self.scope)
            self.scope = merged
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.visit_expr(stmt.iter)
            self._run_loop(stmt.body, rebinds=stmt.target)
            self.run_body(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self.visit_expr(stmt.test)
            self._run_loop(stmt.body)
            self.run_body(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, None)
            self.run_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self.run_body(stmt.body)
            for h in stmt.handlers:
                self.run_body(h.body)
            self.run_body(stmt.orelse)
            self.run_body(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass        # nested scopes are analyzed separately
        elif isinstance(stmt, (ast.Raise, ast.Assert, ast.Delete)):
            for field_val in ast.iter_child_nodes(stmt):
                self.visit_expr(field_val)
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.scope.dead.pop(t.id, None)
                        self.scope.tainted.discard(t.id)
        else:
            for field_val in ast.iter_child_nodes(stmt):
                if isinstance(field_val, ast.expr):
                    self.visit_expr(field_val)

    def _run_loop(self, body: List[ast.stmt],
                  rebinds: Optional[ast.AST] = None):
        """Two passes: the second starts from the first's exit state, so
        a name donated on iteration N and read (even by the donating
        call itself) on iteration N+1 without a rebind is flagged. The
        for-loop target rebinds fresh at the top of every iteration."""
        for _pass in range(2):
            if rebinds is not None:
                self._bind(rebinds, None)
            self.run_body(body)


class DonationSafetyRule(Rule):
    name = RULE
    description = ("use-after-donate and numpy buffers reaching "
                   "donate_argnums parameters")
    paths = ("deeplearning4j_tpu",)

    def prepare(self, project: Project) -> None:
        tables: Dict[str, Dict[str, List[int]]] = {}
        for ctx in project.contexts:
            mod = module_name_of(ctx.rel)
            if mod:
                tables[mod] = module_donators(ctx)
        project.facts[RULE] = tables

    # ---- import resolution -----------------------------------------------
    def _imported_donators(self, ctx: ModuleContext,
                           project: Project) -> Dict[str, List[int]]:
        tables = project.facts.get(RULE, {})
        out: Dict[str, List[int]] = {}
        mod = module_name_of(ctx.rel) or ""
        pkg_parts = mod.split(".")
        is_pkg = ctx.rel.endswith("__init__.py")
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                target = self._resolve_from(node, pkg_parts, is_pkg)
                if target is None:
                    continue
                for a in node.names:
                    # from mod import donating_fn
                    fn_table = tables.get(target, {})
                    if a.name in fn_table:
                        out[a.asname or a.name] = fn_table[a.name]
                    # from pkg import submodule
                    sub = f"{target}.{a.name}"
                    for fn, pos in tables.get(sub, {}).items():
                        out[f"{a.asname or a.name}.{fn}"] = pos
            elif isinstance(node, ast.Import):
                for a in node.names:
                    for fn, pos in tables.get(a.name, {}).items():
                        head = a.asname or a.name
                        out[f"{head}.{fn}"] = pos
        return out

    @staticmethod
    def _resolve_from(node: ast.ImportFrom, pkg_parts: List[str],
                      is_pkg: bool) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative import: level 1 = current package
        base = pkg_parts if is_pkg else pkg_parts[:-1]
        up = node.level - 1
        if up > len(base):
            return None
        base = base[:len(base) - up] if up else base
        if node.module:
            return ".".join(base + node.module.split("."))
        return ".".join(base) if base else None

    # ---- per-module check ------------------------------------------------
    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        aliases = collect_jit_aliases(ctx.tree)
        donators = dict(module_donators(ctx))
        donators.update(self._imported_donators(ctx, project))

        # module top level + every function/method body, each its own
        # linear scope
        scopes: List[List[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            an = _FunctionAnalyzer(self, ctx, donators, aliases)
            an.run_body(body)
            yield from an.findings.values()
