"""tracer-leak: traced values escaping a jit/shard_map trace.

Inside ``jax.jit``/``pjit``/``shard_map``/``pmap``-traced code, every
value derived from an argument is a Tracer. Storing one onto ``self``,
a global, or an enclosing scope outlives the trace: at best a
``TracerLeakError`` under ``jax.check_tracer_leaks``, at worst a stale
abstract value silently captured by the *first* trace and replayed
forever after (the classic "metrics stuck at step 0" bug). Flags, in
any function that is jit-decorated, passed to jax.jit/shard_map/pmap
in the same module, or nested inside such a function:

- assignments to ``self.<attr>`` (and any parameter's attribute)
- assignments to names declared ``global`` / ``nonlocal``
- subscript stores into closure/global names (``cache[k] = x``)

Trace-time configuration writes are rare and explicit — pragma them
with ``# graftlint: disable=tracer-leak: <reason>``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tools.graftlint.engine import (
    Finding, ModuleContext, Project, Rule, collect_jit_aliases,
    dotted_name, is_jit_callable)

RULE = "tracer-leak"

_TRACING_WRAPPERS = ("shard_map", "jax.experimental.shard_map.shard_map",
                     "pmap", "jax.pmap", "vmap_of_jit")


def _is_tracing_call(node: ast.Call, aliases: Set[str]) -> bool:
    if is_jit_callable(node.func, aliases):
        return True
    name = dotted_name(node.func)
    if name is None:
        return False
    return name in _TRACING_WRAPPERS \
        or name.split(".")[-1] in ("shard_map", "pmap")


def _wrapped_names(tree: ast.Module, aliases: Set[str]) -> Set[str]:
    """Names passed (positionally, arg 0, incl. through
    functools.partial) to jit/shard_map/pmap anywhere in the module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_tracing_call(node, aliases) and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name):
                out.add(a.id)
            elif isinstance(a, ast.Call) \
                    and dotted_name(a.func) in ("functools.partial",
                                                "partial") and a.args \
                    and isinstance(a.args[0], ast.Name):
                out.add(a.args[0].id)
    return out


class _LeakVisitor(ast.NodeVisitor):
    """Walks one traced function body; nested defs inherit traced-ness
    (they trace too) but keep their own local-name tables."""

    def __init__(self, ctx: ModuleContext, fn, findings: List[Finding]):
        self.ctx = ctx
        self.findings = findings
        self.fn_name = fn.name if hasattr(fn, "name") else "<lambda>"
        self.locals: Set[str] = {
            a.arg for a in fn.args.args + fn.args.posonlyargs
            + fn.args.kwonlyargs}
        if fn.args.vararg:
            self.locals.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            self.locals.add(fn.args.kwarg.arg)
        self.globals: Set[str] = set()
        self.nonlocals: Set[str] = set()
        for stmt in fn.body:
            self.visit(stmt)

    # ---- scope declarations ---------------------------------------------
    def visit_Global(self, node: ast.Global):
        self.globals.update(node.names)

    def visit_Nonlocal(self, node: ast.Nonlocal):
        self.nonlocals.update(node.names)

    def visit_FunctionDef(self, node):
        self.locals.add(node.name)
        _LeakVisitor(self.ctx, node, self.findings)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    # ---- stores ----------------------------------------------------------
    def _check_target(self, t: ast.expr, lineno: int):
        if isinstance(t, ast.Attribute):
            base = t.value
            if isinstance(base, ast.Name):
                who = ("self" if base.id == "self"
                       else f"parameter '{base.id}'"
                       if base.id in self.locals else base.id)
                self.findings.append(self.ctx.finding(
                    RULE, lineno,
                    f"store to {who}.{t.attr} inside traced function "
                    f"'{self.fn_name}': the traced value outlives the "
                    "trace (leaked Tracer / value frozen at first "
                    "trace) — return it instead, or carry it in the "
                    "function's outputs"))
        elif isinstance(t, ast.Subscript):
            base = t.value
            if isinstance(base, ast.Name) \
                    and base.id not in self.locals:
                self.findings.append(self.ctx.finding(
                    RULE, lineno,
                    f"subscript store into enclosing-scope "
                    f"'{base.id}' inside traced function "
                    f"'{self.fn_name}': mutating host containers "
                    "under trace leaks tracers and runs only on the "
                    "first trace — return the value instead"))
        elif isinstance(t, ast.Name):
            if t.id in self.globals or t.id in self.nonlocals:
                kind = "global" if t.id in self.globals else "nonlocal"
                self.findings.append(self.ctx.finding(
                    RULE, lineno,
                    f"assignment to {kind} '{t.id}' inside traced "
                    f"function '{self.fn_name}': the binding escapes "
                    "the trace and is only updated when (re)tracing — "
                    "thread it through the function's inputs/outputs"))
            else:
                self.locals.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                self._check_target(elt, lineno)
        elif isinstance(t, ast.Starred):
            self._check_target(t.value, lineno)

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        for t in node.targets:
            self._check_target(t, node.lineno)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        self._check_target(node.target, node.lineno)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self.visit(node.value)
            self._check_target(node.target, node.lineno)

    def visit_For(self, node: ast.For):
        # loop targets are local bindings, not leaks
        for sub in ast.walk(node.target):
            if isinstance(sub, ast.Name):
                self.locals.add(sub.id)
        self.visit(node.iter)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_With(self, node: ast.With):
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                for sub in ast.walk(item.optional_vars):
                    if isinstance(sub, ast.Name):
                        self.locals.add(sub.id)
        for stmt in node.body:
            self.visit(stmt)


def _traced_defs(tree: ast.Module, aliases: Set[str]):
    wrapped = _wrapped_names(tree, aliases)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        decorated = False
        for dec in node.decorator_list:
            if is_jit_callable(dec, aliases):
                decorated = True
            elif isinstance(dec, ast.Call):
                if _is_tracing_call(dec, aliases):
                    decorated = True
                elif dotted_name(dec.func) in ("functools.partial",
                                               "partial") \
                        and dec.args \
                        and is_jit_callable(dec.args[0], aliases):
                    decorated = True
        if decorated or node.name in wrapped:
            yield node


class TracerLeakRule(Rule):
    name = RULE
    description = ("traced values stored on self/globals/closures from "
                   "inside jitted or shard_map'd functions")
    paths = ("deeplearning4j_tpu",)

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        aliases = collect_jit_aliases(ctx.tree)
        findings: List[Finding] = []
        seen: Set[int] = set()
        for fn in _traced_defs(ctx.tree, aliases):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            _LeakVisitor(ctx, fn, findings)
        # dedup (a def both decorated and re-wrapped)
        out, keys = [], set()
        for f in findings:
            k = (f.line, f.message)
            if k not in keys:
                keys.add(k)
                out.append(f)
        yield from out
