"""chaos-hygiene: keep the fault-injection layer off the hot paths.

The chaos layer (deeplearning4j_tpu/chaos/) is built around a
zero-overhead disarm contract: hot modules import ONLY the lazy probe
``from deeplearning4j_tpu.chaos.hook import chaos_site``, bind each
site handle ONCE at construction, and guard injection points with a
``if self._chaos_x is not None`` test. When no plan is armed the hook
returns None without ever importing ``chaos.plan`` — the per-request
cost is one attribute probe and a None test.

This rule polices the two ways that contract erodes:

- importing anything from ``deeplearning4j_tpu.chaos`` other than the
  hook's ``chaos_site`` inside a hot path (the package ``__init__`` and
  ``chaos.plan`` pull in the full plan machinery — locks, registry,
  splitmix draws — onto every import of the hot module, armed or not);
- calling ``chaos_site()`` inside a ``for``/``while`` body (the probe
  does an environ + sys.modules check; resolved per-iteration it puts
  dict lookups back on the loop the None-handle pattern exists to
  protect).

Scope: the same ``HOT_PATHS`` the host-sync rule polices — everywhere
a hidden per-iteration cost is a regression.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tools.graftlint.engine import Finding, ModuleContext, Project, Rule
from tools.graftlint.rules.host_sync import HOT_PATHS

_HOOK_MODULE = "deeplearning4j_tpu.chaos.hook"
_CHAOS_PREFIX = "deeplearning4j_tpu.chaos"


class ChaosHygieneRule(Rule):
    name = "chaos-hygiene"
    description = ("fault-injection layer leaking onto hot paths: "
                   "non-hook chaos imports, or chaos_site() resolved "
                   "inside a loop body")
    paths = HOT_PATHS

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == _HOOK_MODULE:
                    for a in node.names:
                        if a.name != "chaos_site":
                            yield ctx.finding(
                                self.name, node.lineno,
                                f"import of {a.name!r} from the chaos "
                                "hook — hot paths may import only "
                                "chaos_site")
                elif mod == _CHAOS_PREFIX \
                        or mod.startswith(_CHAOS_PREFIX + "."):
                    yield ctx.finding(
                        self.name, node.lineno,
                        f"hot path imports {mod!r} — only the lazy "
                        f"probe 'from {_HOOK_MODULE} import "
                        "chaos_site' is allowed (the plan machinery "
                        "must stay un-imported while disarmed)")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == _CHAOS_PREFIX \
                            or a.name.startswith(_CHAOS_PREFIX + "."):
                        yield ctx.finding(
                            self.name, node.lineno,
                            f"hot path imports {a.name!r} — only the "
                            f"lazy probe 'from {_HOOK_MODULE} import "
                            "chaos_site' is allowed")
        seen = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for sub in ast.walk(loop):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                fname = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if fname == "chaos_site" and sub.lineno not in seen:
                    seen.add(sub.lineno)
                    yield ctx.finding(
                        self.name, sub.lineno,
                        "chaos_site() resolved inside a loop body — "
                        "bind the site handle once at construction "
                        "and test 'if handle is not None' in the loop")
