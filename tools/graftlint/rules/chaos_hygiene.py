"""chaos-hygiene: keep the fault-injection layer off the hot paths.

The chaos layer (deeplearning4j_tpu/chaos/) is built around a
zero-overhead disarm contract: hot modules import ONLY the lazy probe
``from deeplearning4j_tpu.chaos.hook import chaos_site``, bind each
site handle ONCE at construction, and guard injection points with a
``if self._chaos_x is not None`` test. When no plan is armed the hook
returns None without ever importing ``chaos.plan`` — the per-request
cost is one attribute probe and a None test.

This rule polices the two ways that contract erodes:

- importing anything from ``deeplearning4j_tpu.chaos`` other than the
  hook's ``chaos_site`` inside a hot path (the package ``__init__`` and
  ``chaos.plan`` pull in the full plan machinery — locks, registry,
  splitmix draws — onto every import of the hot module, armed or not);
- calling ``chaos_site()`` inside a ``for``/``while`` body (the probe
  does an environ + sys.modules check; resolved per-iteration it puts
  dict lookups back on the loop the None-handle pattern exists to
  protect).

Scope: the same ``HOT_PATHS`` the host-sync rule polices — everywhere
a hidden per-iteration cost is a regression.

**Seam-coverage audit** (opt-in, ``--chaos-audit``): the inverse
check. In the cluster modules whose faults the chaos plans exist to
reproduce, a socket operation or file write inside a class that binds
no ``chaos_site`` handle is a seam fault injection cannot reach — a
blind spot in every soak run. Audit findings are advisory (the flag
is off in CI); legitimately uncovered seams (e.g. loopback test
servers) carry a pragma saying why.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from tools.graftlint.engine import (REPO_ROOT, Finding, ModuleContext,
                                    Project, Rule, module_name_of)
from tools.graftlint.rules.host_sync import HOT_PATHS

_HOOK_MODULE = "deeplearning4j_tpu.chaos.hook"
_CHAOS_PREFIX = "deeplearning4j_tpu.chaos"

# modules whose socket/file seams chaos plans are expected to cover
AUDIT_PATHS = (
    "deeplearning4j_tpu/parallel/node.py",
    "deeplearning4j_tpu/parallel/remote.py",
    "deeplearning4j_tpu/parallel/aot_cache.py",
    "deeplearning4j_tpu/parallel/cluster.py",
    "deeplearning4j_tpu/streaming/broker.py",
)

_SOCKET_SUFFIXES = ("urlopen", "create_connection", "socket.socket",
                    "HTTPConnection", "HTTPSConnection", "getresponse")


class ChaosHygieneRule(Rule):
    name = "chaos-hygiene"
    description = ("fault-injection layer leaking onto hot paths: "
                   "non-hook chaos imports, or chaos_site() resolved "
                   "inside a loop body; with --chaos-audit, also "
                   "socket/file-write seams lacking a chaos_site "
                   "handle in the cluster modules")
    paths = HOT_PATHS

    def __init__(self, audit_seams: bool = False):
        self.audit_seams = audit_seams

    def applies(self, ctx: ModuleContext) -> bool:
        if super().applies(ctx):
            return True
        return self.audit_seams and self._audit_applies(ctx)

    def _audit_applies(self, ctx: ModuleContext) -> bool:
        rel = ctx.rel.replace("\\", "/")
        if Path(rel).is_absolute() or ctx.root != REPO_ROOT:
            return True         # fixture corpora: audit everything
        return rel in AUDIT_PATHS

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        if self.audit_seams and self._audit_applies(ctx):
            yield from self._audit(ctx, project)
        if not super().applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == _HOOK_MODULE:
                    for a in node.names:
                        if a.name != "chaos_site":
                            yield ctx.finding(
                                self.name, node.lineno,
                                f"import of {a.name!r} from the chaos "
                                "hook — hot paths may import only "
                                "chaos_site")
                elif mod == _CHAOS_PREFIX \
                        or mod.startswith(_CHAOS_PREFIX + "."):
                    yield ctx.finding(
                        self.name, node.lineno,
                        f"hot path imports {mod!r} — only the lazy "
                        f"probe 'from {_HOOK_MODULE} import "
                        "chaos_site' is allowed (the plan machinery "
                        "must stay un-imported while disarmed)")
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == _CHAOS_PREFIX \
                            or a.name.startswith(_CHAOS_PREFIX + "."):
                        yield ctx.finding(
                            self.name, node.lineno,
                            f"hot path imports {a.name!r} — only the "
                            f"lazy probe 'from {_HOOK_MODULE} import "
                            "chaos_site' is allowed")
        seen = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for sub in ast.walk(loop):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                fname = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else None)
                if fname == "chaos_site" and sub.lineno not in seen:
                    seen.add(sub.lineno)
                    yield ctx.finding(
                        self.name, sub.lineno,
                        "chaos_site() resolved inside a loop body — "
                        "bind the site handle once at construction "
                        "and test 'if handle is not None' in the loop")

    # -- seam-coverage audit (opt-in) ------------------------------------

    def _audit(self, ctx: ModuleContext,
               project: Project) -> Iterable[Finding]:
        mod = module_name_of(ctx.rel) or ctx.rel
        ms = project.summaries.get(mod)
        if ms is None:
            return
        # which classes (and the module-function scope "") bind a
        # chaos_site handle anywhere
        covered = set()
        for s in ms.functions.values():
            scope = s.qname.rsplit(".", 1)[0] if "." in s.qname else ""
            if any(c.callee.split(".")[-1] == "chaos_site"
                   for c in s.calls):
                covered.add(scope)
        for s in ms.functions.values():
            scope = s.qname.rsplit(".", 1)[0] if "." in s.qname else ""
            if scope in covered:
                continue
            seam = None
            for c in s.calls:
                if any(c.callee == suf or c.callee.endswith("." + suf)
                       or c.callee.endswith(suf)
                       for suf in _SOCKET_SUFFIXES):
                    seam = (c.lineno, f"socket op {c.callee}()")
                    break
            if seam is None and s.writes:
                w = s.writes[0]
                seam = (w.lineno, f"file write to {w.target!r}")
            if seam is not None:
                where = scope or "module scope"
                yield ctx.finding(
                    self.name, seam[0],
                    f"audit: {s.qname} has a {seam[1]} but {where} "
                    f"binds no chaos_site handle — fault injection "
                    f"cannot reach this seam")
