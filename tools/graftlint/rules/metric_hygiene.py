"""metric-hygiene: one series, one label set, one catalog entry.

Every ``dl4j_*`` Prometheus series the tree emits must (a) use a
single consistent label set across all emission sites — a series
scraped with ``{session, precision}`` here and ``{session}`` there
splits into incompatible time series and silently breaks dashboards —
and (b) appear in OBSERVABILITY.md's catalog with exactly that label
set. Drift in either direction is a finding.

The emission map comes from the summary layer and resolves the
repo's three registration idioms:

- handle on ``self`` bound in ``__init__`` and emitted from other
  methods (``self._c_dispatch.inc(1.0, node=n, outcome=o)``);
- the inline chain ``reg.gauge("dl4j_x", h).set(v, session=s)``;
- name-through-parameter indirection
  (``cluster.py::_bump_counter(name)``) — the interprocedural case:
  the template's label set attaches to every literal series name a
  resolved call site passes in.

The catalog side is a **strict** parse of OBSERVABILITY.md: a series
is cataloged by a backticked ``dl4j_name{label, label}`` token
(``{}`` for label-less series); a backticked ``dl4j_*`` token with
malformed braces is itself a finding (reported against the doc file),
as is a series documented with two different label sets. Bare
backticked names without braces are prose references, not entries.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

from tools.graftlint.engine import (Finding, ModuleContext, Project,
                                    Rule, module_name_of)

CATALOG_NAME = "OBSERVABILITY.md"

_TOKEN_RX = re.compile(r"`([^`]+)`")
_ENTRY_RX = re.compile(r"^(dl4j_\w+)\{([^{}]*)\}$")
_BARE_RX = re.compile(r"^(dl4j_\w+)$")
_LABEL_RX = re.compile(r"^\w+$")


def parse_catalog(text: str) -> Tuple[Dict[str, Tuple[str, ...]],
                                      List[Tuple[int, str]]]:
    """OBSERVABILITY.md text -> ({series: sorted label tuple},
    [(lineno, error)]). Strict: malformed dl4j_ tokens and
    conflicting duplicate entries are errors, not guesses."""
    entries: Dict[str, Tuple[str, ...]] = {}
    lines: Dict[str, int] = {}
    errors: List[Tuple[int, str]] = []
    for i, line in enumerate(text.splitlines(), 1):
        for m in _TOKEN_RX.finditer(line):
            tok = m.group(1).strip()
            if not tok.startswith("dl4j_"):
                continue
            if "{" not in tok and "}" not in tok:
                # bare names, `dl4j_foo_*` families, alert expressions:
                # prose references, not catalog entries
                continue
            em = _ENTRY_RX.match(tok)
            if em:
                name = em.group(1)
                raw = [p.strip() for p in em.group(2).split(",")
                       if p.strip()]
                bad = [p for p in raw
                       if not _LABEL_RX.match(p.split("=")[0].strip())]
                if bad:
                    errors.append(
                        (i, f"malformed label(s) {bad} in catalog "
                            f"entry {tok!r}"))
                    continue
                labels = tuple(sorted(p.split("=")[0].strip()
                                      for p in raw))
                if name in entries and entries[name] != labels:
                    errors.append(
                        (i, f"{name} cataloged twice with different "
                            f"label sets: {{{', '.join(entries[name])}}}"
                            f" (line {lines[name]}) vs "
                            f"{{{', '.join(labels)}}}"))
                    continue
                entries[name] = labels
                lines.setdefault(name, i)
            elif not _BARE_RX.match(tok):
                errors.append(
                    (i, f"unparseable dl4j_ token {tok!r} in catalog "
                        f"— expected dl4j_name or dl4j_name{{labels}}"))
    return entries, errors


def _fmt(labels: Tuple[str, ...]) -> str:
    return "{" + ", ".join(labels) + "}"


class _Emission:
    __slots__ = ("name", "labels", "has_star", "module", "rel",
                 "lineno")

    def __init__(self, name, labels, has_star, module, rel, lineno):
        self.name = name
        self.labels = labels
        self.has_star = has_star
        self.module = module
        self.rel = rel
        self.lineno = lineno


class MetricHygieneRule(Rule):
    name = "metric-hygiene"
    description = ("every dl4j_* series must use one consistent label "
                   "set across all emission sites and appear in "
                   "OBSERVABILITY.md's catalog with that label set")

    def prepare(self, project: Project) -> None:
        catalog = None
        errors: List[Tuple[int, str]] = []
        cat_path = Path(project.root) / CATALOG_NAME
        if cat_path.exists():
            catalog, errors = parse_catalog(
                cat_path.read_text(encoding="utf-8"))
        emissions = self._emission_map(project)
        # reference label set per series for cross-site consistency
        # when the catalog has no entry: majority wins, earliest
        # emission breaks ties (deterministic)
        reference: Dict[str, Tuple[str, ...]] = {}
        for name, ems in emissions.items():
            votes: Dict[Tuple[str, ...], int] = {}
            for e in ems:
                if not e.has_star:
                    votes[e.labels] = votes.get(e.labels, 0) + 1
            if votes:
                best = max(votes.values())
                winners = [l for l, n in votes.items() if n == best]
                order = {e.labels: i for i, e in
                         enumerate(reversed(ems)) if not e.has_star}
                winners.sort(key=lambda l: (order.get(l, 0), l))
                reference[name] = winners[0]
        project.facts[self.name] = {
            "catalog": catalog, "errors": errors, "path": cat_path,
            "emissions": emissions, "reference": reference}

    # -- emission map ----------------------------------------------------

    def _emission_map(self, project: Project
                      ) -> Dict[str, List[_Emission]]:
        cg = project.callgraph
        # (module, Class, "self.attr") -> literal series name
        attr_names: Dict[Tuple[str, str, str], str] = {}
        # key of template fn -> (param index, emit labels, has_star)
        templates: Dict[str, List[Tuple[int, Tuple[str, ...], bool]]] \
            = {}
        for ms in project.summaries.values():
            for s in ms.functions.values():
                cls = s.qname.rsplit(".", 1)[0] if "." in s.qname \
                    else ""
                for d in s.metric_defs:
                    if d.name and d.binding \
                            and d.binding.startswith("self."):
                        attr_names[(s.module, cls, d.binding)] = d.name
                for e in s.metric_emits:
                    if e.name_param and e.name_param in s.params:
                        templates.setdefault(s.key, []).append(
                            (s.params.index(e.name_param), e.labels,
                             e.has_star))
        out: Dict[str, List[_Emission]] = {}

        def add(name, labels, star, s, lineno, ms):
            if name and name.startswith("dl4j_"):
                out.setdefault(name, []).append(_Emission(
                    name, labels, star, s.module, ms.rel, lineno))

        for ms in project.summaries.values():
            for s in ms.functions.values():
                cls = s.qname.rsplit(".", 1)[0] if "." in s.qname \
                    else ""
                # local handle -> name, for same-function bindings
                local = {d.binding: d.name for d in s.metric_defs
                         if d.name and d.binding
                         and not d.binding.startswith("self.")}
                for e in s.metric_emits:
                    if e.name:
                        add(e.name, e.labels, e.has_star, s,
                            e.lineno, ms)
                    elif e.handle:
                        name = local.get(e.handle) or attr_names.get(
                            (s.module, cls, e.handle))
                        if name:
                            add(name, e.labels, e.has_star, s,
                                e.lineno, ms)
                # name-through-parameter: literal call sites into
                # template functions
                for cs in s.calls:
                    for tgt in cg.resolve(s.module, s.qname,
                                          cs.callee):
                        for idx, labels, star in templates.get(
                                tgt, ()):
                            tparams = cg.functions[tgt].params
                            if tparams and tparams[0] in ("self",
                                                          "cls"):
                                idx -= 1
                            for j in (idx, idx + 1):
                                if 0 <= j < len(cs.literal_args) \
                                        and cs.literal_args[j]:
                                    add(cs.literal_args[j], labels,
                                        star, s, cs.lineno, ms)
                                    break
        for ems in out.values():
            ems.sort(key=lambda e: (e.rel, e.lineno))
        return out

    # -- findings --------------------------------------------------------

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        facts = project.facts.get(self.name)
        if not facts or ctx.tree is None:
            return
        mod = module_name_of(ctx.rel) or ctx.rel
        catalog: Optional[Dict[str, Tuple[str, ...]]] = \
            facts["catalog"]
        reference = facts["reference"]
        for name, ems in sorted(facts["emissions"].items()):
            for e in ems:
                if e.module != mod or e.has_star:
                    continue
                if catalog is not None:
                    if name not in catalog:
                        yield ctx.finding(
                            self.name, e.lineno,
                            f"series {name} is not in "
                            f"{CATALOG_NAME}'s catalog — document it "
                            f"as `{name}{_fmt(e.labels)}` or drop the "
                            f"emission")
                        continue
                    want = catalog[name]
                    if e.labels != want:
                        yield ctx.finding(
                            self.name, e.lineno,
                            f"series {name} emitted with labels "
                            f"{_fmt(e.labels)} but cataloged as "
                            f"{_fmt(want)} — dashboards split on "
                            f"label drift")
                elif reference.get(name) is not None \
                        and e.labels != reference[name]:
                    yield ctx.finding(
                        self.name, e.lineno,
                        f"series {name} emitted with labels "
                        f"{_fmt(e.labels)} here but "
                        f"{_fmt(reference[name])} at its other "
                        f"sites — one series, one label set")

    def project_findings(self, project: Project
                         ) -> Iterable[Finding]:
        facts = project.facts.get(self.name)
        if not facts:
            return
        cat_path: Path = facts["path"]
        lines: List[str] = []
        if cat_path.exists():
            lines = cat_path.read_text(
                encoding="utf-8").splitlines()
        for lineno, msg in facts["errors"]:
            snippet = lines[lineno - 1].strip() \
                if 0 < lineno <= len(lines) else ""
            yield Finding(rule=self.name, path=cat_path,
                          line=lineno, message=msg, snippet=snippet)
