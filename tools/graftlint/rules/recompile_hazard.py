"""recompile-hazard: jit constructions that defeat the trace cache.

The feeder's bucket ladder and the serving engine's warmed AOT table
both depend on a *stable* set of (function, signature) keys. Each of
these shapes silently mints new executables instead:

- **jit-in-loop** — ``jax.jit(...)`` constructed inside a for/while
  body: every iteration builds a fresh wrapper with its own empty trace
  cache, so every iteration recompiles.
- **jit-per-call** — ``jax.jit(f)(x)`` immediately invoked, or
  ``jax.jit`` applied to a ``lambda`` inside a function body that is
  not a one-time builder: the wrapper (and for a lambda, the function
  identity itself) is fresh per call, so the compile cache can never
  hit. One-time builders (``__init__``, ``build_*``/``make_*``/
  ``_warmup*`` and module level) are exempt — constructing a jit once
  per object is the intended pattern.
- **data-dependent-static** — ``int(x)``/``float(x)``/``x.item()``
  results passed at a ``static_argnums`` position of a jitted callable
  defined in the same module: every distinct runtime value is a new
  cache key (plus a host sync to read it).
- **traced-branch** — a Python ``if``/``while`` testing a bare
  parameter of a jit-decorated function: the test either raises a
  ConcretizationTypeError or, with that parameter made static, turns
  every distinct value into a recompile. ``.shape``/``.dtype``/
  ``.ndim``/``len()`` uses are trace-time constants and stay exempt.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from tools.graftlint.engine import (
    Finding, ModuleContext, Project, Rule, collect_jit_aliases,
    dotted_name, is_jit_callable, literal_argnums)

RULE = "recompile-hazard"

# function names allowed to construct jits per call: one-time builders
# and warmup paths, where construction is the *point*
_BUILDER_RX = re.compile(
    r"^(?:__init__|_?build_\w*|_?make_\w*|_?create_\w*|_?compile\w*|"
    r"_?warmup\w*|_?get_exe\w*|_?init\w*|setup\w*)$")

_SYNC_READ_RX = ("int", "float")


def _is_partial_jit(call: ast.Call, aliases: Set[str]) -> bool:
    return dotted_name(call.func) in ("functools.partial", "partial") \
        and bool(call.args) and is_jit_callable(call.args[0], aliases)


def _static_positions(call: ast.Call,
                      aliases: Set[str]) -> Optional[List[int]]:
    if not (is_jit_callable(call.func, aliases)
            or _is_partial_jit(call, aliases)):
        return None
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            return literal_argnums(kw.value)
    return None


def _is_sync_read(node: ast.AST) -> bool:
    """int(x)/float(x)/x.item(): a host read of a (potentially) device
    value — as a static arg it keys the cache on runtime data."""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Name) \
            and node.func.id in _SYNC_READ_RX and node.args:
        # int(x.shape[0]) and friends are trace-time: exempt shape math
        inner = node.args[0]
        for sub in ast.walk(inner):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("shape", "ndim", "size", "dtype"):
                return False
        return True
    if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
        return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule: "RecompileHazardRule", ctx: ModuleContext,
                 aliases: Set[str],
                 static_table: Dict[str, List[int]]):
        self.rule = rule
        self.ctx = ctx
        self.aliases = aliases
        self.static_table = static_table
        self.loop_depth = 0
        self.func_stack: List[str] = []       # enclosing function names
        self.findings: List[Finding] = []

    # ---- scope bookkeeping ---------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._enter_function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._enter_function(node)

    def _enter_function(self, node):
        for dec in node.decorator_list:
            self.visit(dec)
        name = node.name
        if any(self._is_memoizer(d) for d in node.decorator_list):
            # lru_cache/cache-decorated: the body runs once per key, so
            # jit construction inside IS the construct-once pattern
            name = "__memoized_builder__"
        self.func_stack.append(name)
        outer_loop, self.loop_depth = self.loop_depth, 0
        for stmt in node.body:
            self.visit(stmt)
        self.loop_depth = outer_loop
        self.func_stack.pop()

    def visit_For(self, node):
        self._loop(node)

    def visit_AsyncFor(self, node):
        self._loop(node)

    def visit_While(self, node):
        self._loop(node)

    def _loop(self, node):
        for value in ast.iter_child_nodes(node):
            if isinstance(value, ast.expr):
                self.visit(value)
        self.loop_depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        self.loop_depth -= 1

    # ---- the checks -----------------------------------------------------
    @staticmethod
    def _is_memoizer(dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            dec = dec.func
        return dotted_name(dec) in (
            "functools.lru_cache", "functools.cache", "lru_cache",
            "cache")

    def _in_builder(self) -> bool:
        return any(n == "__memoized_builder__" or _BUILDER_RX.match(n)
                   for n in self.func_stack)

    def visit_Call(self, node: ast.Call):
        jitty = is_jit_callable(node.func, self.aliases) \
            or _is_partial_jit(node, self.aliases)
        if jitty and self.loop_depth > 0:
            self.findings.append(self.ctx.finding(
                RULE, node.lineno,
                "jax.jit constructed inside a loop: each iteration "
                "builds a fresh wrapper with an empty trace cache "
                "(recompiles every pass) — hoist the jit out of the "
                "loop"))
        elif jitty and self.func_stack and not self._in_builder():
            # inside a per-call method: flag fresh-identity wrapping
            wrapped = node.args[0] if node.args else None
            if isinstance(wrapped, ast.Lambda):
                self.findings.append(self.ctx.finding(
                    RULE, node.lineno,
                    f"jax.jit(lambda ...) inside "
                    f"'{self.func_stack[-1]}()': the lambda is a fresh "
                    "function identity per call, so this recompiles "
                    "every invocation — build it once in __init__/a "
                    "builder and reuse"))
        # jax.jit(...)(args): immediately-invoked wrapper — fresh trace
        # cache per call regardless of what it wraps
        if isinstance(node.func, ast.Call) \
                and (is_jit_callable(node.func.func, self.aliases)
                     or _is_partial_jit(node.func, self.aliases)) \
                and self.func_stack and not self._in_builder():
            self.findings.append(self.ctx.finding(
                RULE, node.lineno,
                "jax.jit(...) constructed and invoked in one "
                "expression: the wrapper's compile cache dies with the "
                "expression, so every call recompiles — bind the "
                "jitted callable once and reuse it"))
        # data-dependent static args on calls to known static-jitted fns
        callee = dotted_name(node.func)
        if callee in self.static_table:
            for p in self.static_table[callee]:
                if p < len(node.args) and _is_sync_read(node.args[p]):
                    self.findings.append(self.ctx.finding(
                        RULE, node.lineno,
                        f"data-dependent value at static_argnums "
                        f"position {p} of '{callee}': every distinct "
                        "runtime value recompiles (and the int()/"
                        "float()/.item() read syncs the host) — pass "
                        "it traced, or derive it from shapes"))
        self.generic_visit(node)


class _TracedBranchVisitor(ast.NodeVisitor):
    """Flags ``if param:`` / ``while param > 0:`` on bare parameters of
    jit-decorated functions."""

    def __init__(self, ctx: ModuleContext, fn, params: Set[str]):
        self.ctx = ctx
        self.params = params
        self.findings: List[Finding] = []
        for stmt in fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):      # nested defs: own params
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def _check_test(self, test: ast.expr):
        for sub in ast.walk(test):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("shape", "ndim", "dtype", "size"):
                return            # shape math: trace-time constant
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in self.params:
                self.findings.append(self.ctx.finding(
                    RULE, test.lineno,
                    f"Python branch on traced parameter '{sub.id}' "
                    "inside a jitted function: this either fails to "
                    "trace or (made static) recompiles per value — "
                    "use lax.cond / jnp.where, or branch on shapes"))
                return

    def visit_If(self, node: ast.If):
        self._check_test(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_test(node.test)
        self.generic_visit(node)


def _jitted_functions(tree: ast.Module, aliases: Set[str]):
    """(FunctionDef, params) for defs decorated with jax.jit /
    partial(jax.jit, ...) or passed to jax.jit by name at module
    level."""
    jitted_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and (is_jit_callable(node.func, aliases)
                     or _is_partial_jit(node, aliases)):
            args = node.args[1:] if _is_partial_jit(node, aliases) \
                else node.args
            for a in args[:1]:
                if isinstance(a, ast.Name):
                    jitted_names.add(a.id)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        decorated = False
        static_pos: List[int] = []
        for dec in node.decorator_list:
            if is_jit_callable(dec, aliases):
                decorated = True
            elif isinstance(dec, ast.Call) \
                    and (is_jit_callable(dec.func, aliases)
                         or _is_partial_jit(dec, aliases)):
                decorated = True
                static_pos = _static_positions(dec, aliases) or []
        if decorated or node.name in jitted_names:
            pos_args = node.args.posonlyargs + node.args.args
            params = {a.arg for i, a in enumerate(pos_args)
                      if a.arg not in ("self", "cls")
                      and i not in static_pos}
            params |= {a.arg for a in node.args.kwonlyargs}
            yield node, params


class RecompileHazardRule(Rule):
    name = RULE
    description = ("jit construction in loops/per-call paths, "
                   "data-dependent static args, traced-value branches")
    paths = ("deeplearning4j_tpu",)

    def check(self, ctx: ModuleContext,
              project: Project) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        aliases = collect_jit_aliases(ctx.tree)
        # module-level map: name -> static positions (for the
        # data-dependent-static check)
        static_table: Dict[str, List[int]] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                pos = _static_positions(node.value, aliases)
                if pos:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            static_table[t.id] = pos
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _static_positions(dec, aliases)
                        if pos:
                            static_table[node.name] = pos
        v = _Visitor(self, ctx, aliases, static_table)
        v.visit(ctx.tree)
        yield from v.findings
        for fn, params in _jitted_functions(ctx.tree, aliases):
            yield from _TracedBranchVisitor(ctx, fn, params).findings
