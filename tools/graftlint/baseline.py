"""Baseline file: the triaged-backlog mechanism.

A finding's identity must survive unrelated edits, so the fingerprint
hashes (rule, repo-relative path, stripped source line, occurrence
index among identical lines in that file) — NOT the line number. Moving
code within a file keeps its baseline entry; editing the flagged line
(or fixing it) invalidates the entry, which is exactly the trigger for
a re-triage.

Workflow::

    python -m tools.graftlint --write-baseline          # triage snapshot
    python -m tools.graftlint --baseline tools/graftlint/baseline.json

CI runs the second form: any finding not in the committed baseline
fails the build; baselined findings are reported but don't fail.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from tools.graftlint.engine import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def _occurrence_indices(findings: Sequence[Finding]) -> List[int]:
    """For each finding, its index among same-(rule, rel, snippet)
    findings seen so far — disambiguates identical lines in one file."""
    counts: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.rel, f.snippet)
        out.append(counts.get(key, 0))
        counts[key] = counts.get(key, 0) + 1
    return out


def fingerprint(f: Finding, occurrence: int = 0) -> str:
    payload = f"{f.rule}|{f.rel}|{f.snippet}|{occurrence}"
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


def fingerprints(findings: Sequence[Finding]) -> List[str]:
    return [fingerprint(f, occ) for f, occ in
            zip(findings, _occurrence_indices(findings))]


def write_baseline(findings: Sequence[Finding], path: Path) -> int:
    entries = {}
    for f, fp in zip(findings, fingerprints(findings)):
        entries[fp] = {"rule": f.rule, "path": f.rel, "line": f.line,
                       "message": f.message, "snippet": f.snippet}
    path.write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": entries},
        indent=2, sort_keys=True) + "\n", encoding="utf-8")
    return len(entries)


def load_baseline(path: Path) -> Dict[str, dict]:
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version "
            f"{data.get('version')!r} (want {BASELINE_VERSION})")
    return dict(data.get("findings", {}))


def split_baselined(findings: Sequence[Finding],
                    baseline: Dict[str, dict]
                    ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """-> (new, baselined, stale_fingerprints). Stale entries are
    baseline lines that no current finding matches — fixed or edited
    code whose entry should be pruned at the next --write-baseline."""
    new, old = [], []
    seen = set()
    for f, fp in zip(findings, fingerprints(findings)):
        if fp in baseline:
            old.append(f)
            seen.add(fp)
        else:
            new.append(f)
    stale = [fp for fp in baseline if fp not in seen]
    return new, old, stale
