"""Per-function summaries: the facts interprocedural rules run on.

One summary is computed per function/method (a single AST pass per
module) and captures everything the distributed-systems rule pack
needs without re-walking the tree per rule:

- **calls** — every call site with its dotted callee, keyword names,
  ``**kwargs`` forwarding, and whether any argument is derived from a
  deadline (`deadline-propagation`).
- **resource issues** — a CFG-lite abstract interpretation over
  ``.acquire()`` / inflight-counter increments: paths (including
  exception edges) where the resource is not released, and
  re-acquire-before-release in loops (`release-discipline`).
- **file writes** — direct writes vs the ``tmp + os.replace``
  protocol (`atomic-write`).
- **metric defs/emits** — ``dl4j_*`` series registrations and their
  emission label sets, including name-through-parameter indirection
  (`metric-hygiene`).

Summaries are plain dataclasses with a stable dict round-trip so the
content-hash cache (tools/graftlint/cache.py) can persist them beside
the baseline and skip re-analysis of unchanged files.
"""

from __future__ import annotations

import ast
import re
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

SUMMARY_VERSION = 1

# counter-shaped names that denote a capacity resource (released
# elsewhere), as opposed to monotonic telemetry counters
_RESOURCE_NAME_RX = re.compile(
    r"(inflight|in_flight|pending|active|busy|claim|slot|lease|permit)",
    re.IGNORECASE)

# identifiers / literals that mark a write target as the tmp half of
# the tmp + os.replace protocol
_TMP_TEXT_RX = re.compile(r"tmp|temp", re.IGNORECASE)

_DEADLINE_CTORS = ("Deadline", "Deadline.from_ingress",
                   "Deadline.after_ms")


@dataclass(frozen=True)
class CallSite:
    """One call expression inside a function body."""
    callee: str                       # dotted name as written
    lineno: int
    kwnames: Tuple[str, ...] = ()
    has_star_kw: bool = False         # **kwargs forwarded
    passes_deadline: bool = False     # deadline kwarg or tainted arg
    literal_args: Tuple[Optional[str], ...] = ()  # str consts by position


@dataclass(frozen=True)
class ResourceIssue:
    """A path on which an acquired resource is not (yet) released."""
    kind: str            # "exception" | "exit" | "reacquire"
    key: str             # dotted resource, e.g. "self._inflight"
    lineno: int          # where the problem manifests
    acquire_lineno: int  # where the resource was acquired


@dataclass(frozen=True)
class FileWrite:
    """A write landing on the filesystem (open/w, write_text, ...)."""
    lineno: int
    target: str          # source text of the destination expression
    tmp_like: bool       # destination is the tmp half of the protocol
    via: str             # "open" | "fdopen" | "write_text" | "write_bytes"


@dataclass(frozen=True)
class MetricDef:
    """A ``registry.counter/gauge(name, help)`` registration."""
    kind: str                      # "counter" | "gauge"
    name: Optional[str]            # literal series name, if constant
    name_param: Optional[str]      # enclosing-fn param carrying the name
    binding: Optional[str]         # "self._c_x" / "g" the handle binds to
    lineno: int = 0


@dataclass(frozen=True)
class MetricEmit:
    """A ``handle.inc(...)`` / ``handle.set(...)`` emission site."""
    name: Optional[str]            # resolved when chained on the def
    name_param: Optional[str]
    handle: Optional[str]          # dotted receiver when not inline
    method: str                    # "inc" | "set"
    labels: Tuple[str, ...] = ()
    has_star: bool = False
    lineno: int = 0


@dataclass(frozen=True)
class FunctionSummary:
    """Everything the interprocedural pass knows about one function."""
    qname: str                     # "Class.method" or "func"
    module: str                    # dotted module name ("" if unknown)
    lineno: int
    params: Tuple[str, ...] = ()
    has_varkw: bool = False
    calls: Tuple[CallSite, ...] = ()
    has_deadline: bool = False     # deadline param or local binding
    deadline_lineno: int = 0
    resource_issues: Tuple[ResourceIssue, ...] = ()
    writes: Tuple[FileWrite, ...] = ()
    metric_defs: Tuple[MetricDef, ...] = ()
    metric_emits: Tuple[MetricEmit, ...] = ()

    @property
    def key(self) -> str:
        return f"{self.module}::{self.qname}"


@dataclass
class ModuleSummary:
    """All function summaries of one module plus its import table."""
    module: str
    rel: str
    functions: Dict[str, FunctionSummary] = field(default_factory=dict)
    # local alias -> dotted target ("pkg.mod" or "pkg.mod.attr")
    imports: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"module": self.module, "rel": self.rel,
                "imports": dict(self.imports),
                "functions": {q: asdict(s)
                              for q, s in self.functions.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        ms = cls(module=d["module"], rel=d["rel"],
                 imports=dict(d.get("imports", {})))
        for q, sd in d.get("functions", {}).items():
            ms.functions[q] = FunctionSummary(
                qname=sd["qname"], module=sd["module"],
                lineno=sd["lineno"], params=tuple(sd["params"]),
                has_varkw=sd["has_varkw"],
                calls=tuple(CallSite(
                    callee=c["callee"], lineno=c["lineno"],
                    kwnames=tuple(c["kwnames"]),
                    has_star_kw=c["has_star_kw"],
                    passes_deadline=c["passes_deadline"],
                    literal_args=tuple(c["literal_args"]))
                    for c in sd["calls"]),
                has_deadline=sd["has_deadline"],
                deadline_lineno=sd["deadline_lineno"],
                resource_issues=tuple(ResourceIssue(**r)
                                      for r in sd["resource_issues"]),
                writes=tuple(FileWrite(**w) for w in sd["writes"]),
                metric_defs=tuple(MetricDef(**m)
                                  for m in sd["metric_defs"]),
                metric_emits=tuple(MetricEmit(
                    **{**m, "labels": tuple(m["labels"])})
                    for m in sd["metric_emits"]))
        return ms


# ---- module-level driver ------------------------------------------------

def build_module_summary(tree: ast.Module, text: str, module: str,
                         rel: str) -> ModuleSummary:
    """One pass over a parsed module -> its ModuleSummary."""
    ms = ModuleSummary(module=module or "", rel=rel)
    ms.imports = _import_table(tree, module or "", rel)
    for node in tree.body:
        _collect(node, text, module or "", ms, prefix="")
    return ms


def _collect(node: ast.AST, text: str, module: str, ms: ModuleSummary,
             prefix: str) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        qname = f"{prefix}{node.name}"
        ms.functions[qname] = _summarize_function(
            node, text, module, qname)
        # nested defs get their own (rarely-resolved) summaries too
        for sub in node.body:
            _collect(sub, text, module, ms, prefix=f"{qname}.")
    elif isinstance(node, ast.ClassDef):
        for sub in node.body:
            _collect(sub, text, module, ms, prefix=f"{node.name}.")
    elif isinstance(node, (ast.If, ast.Try)):
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                _collect(sub, text, module, ms, prefix=prefix)


def _is_pkg(rel: str) -> bool:
    return rel.replace("\\", "/").endswith("/__init__.py")


def _import_table(tree: ast.Module, module: str, rel: str
                  ) -> Dict[str, str]:
    """Local alias -> dotted target, resolving relative imports the
    same way donation-safety does."""
    pkg_parts = module.split(".") if module else []
    if module and not _is_pkg(rel):
        pkg_parts = pkg_parts[:-1]
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
                if a.asname:
                    out[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_from(node, pkg_parts)
            if base is None:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{base}.{a.name}"
    return out


def _resolve_from(node: ast.ImportFrom,
                  pkg_parts: Sequence[str]) -> Optional[str]:
    if node.level == 0:
        return node.module
    # relative import: strip (level - 1) trailing package components
    up = node.level - 1
    if up > len(pkg_parts):
        return None
    base = list(pkg_parts[:len(pkg_parts) - up])
    if node.module:
        base.extend(node.module.split("."))
    return ".".join(base) if base else None


# ---- per-function summarization -----------------------------------------

def _summarize_function(fn, text: str, module: str,
                        qname: str) -> FunctionSummary:
    params = _param_names(fn)
    tainted, dl_lineno = _deadline_taint(fn, params)
    calls = _collect_calls(fn, tainted)
    writes = _collect_writes(fn, text)
    mdefs, memits = _collect_metrics(fn, params)
    issues = _ResourceAnalyzer().run(fn)
    return FunctionSummary(
        qname=qname, module=module, lineno=fn.lineno,
        params=params, has_varkw=fn.args.kwarg is not None,
        calls=tuple(calls), has_deadline=bool(tainted),
        deadline_lineno=dl_lineno, resource_issues=tuple(issues),
        writes=tuple(writes), metric_defs=tuple(mdefs),
        metric_emits=tuple(memits))


def _param_names(fn) -> Tuple[str, ...]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    return tuple(names)


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _walk_own(fn):
    """Walk the function subtree, skipping nested class bodies (their
    methods are summarized separately) but including closures (their
    calls usually run on behalf of this function)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _deadline_taint(fn, params: Sequence[str]
                    ) -> Tuple[Set[str], int]:
    """Names in ``fn`` holding a deadline: the ``deadline`` parameter
    plus locals (transitively) assigned from it or from a Deadline
    constructor."""
    tainted: Set[str] = set()
    lineno = 0
    if "deadline" in params:
        tainted.add("deadline")
        lineno = fn.lineno
    assigns = [n for n in _walk_own(fn) if isinstance(n, ast.Assign)]
    for _ in range(3):                      # tiny transitive closure
        changed = False
        for n in assigns:
            if _mentions_tainted(n.value, tainted) \
                    or _is_deadline_ctor(n.value):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id not in tainted:
                        tainted.add(tgt.id)
                        lineno = lineno or n.lineno
                        changed = True
        if not changed:
            break
    return tainted, lineno


def _is_deadline_ctor(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = _dotted(sub.func)
            if name and (name in _DEADLINE_CTORS
                         or name.endswith(".Deadline")
                         or any(name.endswith("." + c)
                                for c in _DEADLINE_CTORS[1:])):
                return True
    return False


def _mentions_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    if not tainted:
        return False
    return any(isinstance(sub, ast.Name) and sub.id in tainted
               for sub in ast.walk(node))


def _collect_calls(fn, tainted: Set[str]) -> List[CallSite]:
    out: List[CallSite] = []
    for node in _walk_own(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee is None:
            continue
        kwnames = tuple(kw.arg for kw in node.keywords if kw.arg)
        has_star = any(kw.arg is None for kw in node.keywords)
        passes = "deadline" in kwnames or any(
            _mentions_tainted(a, tainted) for a in node.args) or any(
            _mentions_tainted(kw.value, tainted) for kw in node.keywords)
        lits = tuple(a.value if isinstance(a, ast.Constant)
                     and isinstance(a.value, str) else None
                     for a in node.args)
        out.append(CallSite(callee=callee, lineno=node.lineno,
                            kwnames=kwnames, has_star_kw=has_star,
                            passes_deadline=passes, literal_args=lits))
    out.sort(key=lambda c: c.lineno)
    return out


# ---- file-write protocol ------------------------------------------------

_WRITE_MODES = ("w", "a", "x")


def _collect_writes(fn, text: str) -> List[FileWrite]:
    tmp_names = _tmp_tainted_names(fn)
    out: List[FileWrite] = []
    for node in _walk_own(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee in ("open", "io.open") and node.args:
            mode = _open_mode(node)
            if mode is None or not any(m in mode for m in _WRITE_MODES):
                continue
            tgt = node.args[0]
            out.append(_mk_write(tgt, node.lineno, "open",
                                 text, tmp_names))
        elif callee in ("os.fdopen",) and node.args:
            mode = _open_mode(node)
            if mode is not None and not any(m in mode
                                            for m in _WRITE_MODES):
                continue
            out.append(_mk_write(node.args[0], node.lineno, "fdopen",
                                 text, tmp_names))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("write_text", "write_bytes"):
            out.append(_mk_write(node.func.value, node.lineno,
                                 node.func.attr, text, tmp_names))
    out.sort(key=lambda w: w.lineno)
    return out


def _open_mode(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    if len(call.args) == 1 and not any(kw.arg == "mode"
                                       for kw in call.keywords):
        return "r"
    return None


def _tmp_tainted_names(fn) -> Set[str]:
    """Names bound from tempfile.* — always the tmp half."""
    names: Set[str] = set()
    for node in _walk_own(fn):
        if not isinstance(node, ast.Assign):
            continue
        src = node.value
        call = src if isinstance(src, ast.Call) else None
        if call is None:
            continue
        callee = _dotted(call.func) or ""
        if callee.startswith("tempfile.") or callee in (
                "mkstemp", "mktemp", "NamedTemporaryFile"):
            for tgt in node.targets:
                for el in ([tgt] if isinstance(tgt, ast.Name)
                           else getattr(tgt, "elts", [])):
                    if isinstance(el, ast.Name):
                        names.add(el.id)
    return names


def _mk_write(target: ast.AST, lineno: int, via: str, text: str,
              tmp_names: Set[str]) -> FileWrite:
    seg = None
    try:
        seg = ast.get_source_segment(text, target)
    except Exception:
        pass
    if seg is None:
        seg = _dotted(target) or "<expr>"
    seg = " ".join(seg.split())
    tmp_like = bool(_TMP_TEXT_RX.search(seg)) or any(
        isinstance(sub, ast.Name) and sub.id in tmp_names
        for sub in ast.walk(target))
    return FileWrite(lineno=lineno, target=seg[:120],
                     tmp_like=tmp_like, via=via)


# ---- metric defs / emits ------------------------------------------------

def _collect_metrics(fn, params: Sequence[str]
                     ) -> Tuple[List[MetricDef], List[MetricEmit]]:
    defs: List[MetricDef] = []
    emits: List[MetricEmit] = []
    param_set = set(params)
    def_ids: Set[int] = set()      # def Call nodes consumed inline

    # inline chains first: reg.counter("n", h).inc(...) — the emit
    # carries the series name directly
    for node in _walk_own(fn):
        if not isinstance(node, ast.Call):
            continue
        meth = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        if meth not in ("inc", "set"):
            continue
        recv = node.func.value
        d = _match_metric_def(recv, param_set)
        labels = tuple(sorted(kw.arg for kw in node.keywords if kw.arg))
        has_star = any(kw.arg is None for kw in node.keywords)
        if d is not None:
            def_ids.add(id(recv))
            emits.append(MetricEmit(
                name=d.name, name_param=d.name_param, handle=None,
                method=meth, labels=labels, has_star=has_star,
                lineno=node.lineno))
        else:
            handle = _dotted(recv)
            if handle is not None:
                emits.append(MetricEmit(
                    name=None, name_param=None, handle=handle,
                    method=meth, labels=labels, has_star=has_star,
                    lineno=node.lineno))

    # standalone defs (bound to a name / attribute, or bare)
    for node in _walk_own(fn):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            d = _match_metric_def(node.value, param_set)
            if d is not None and id(node.value) not in def_ids:
                binding = None
                if len(node.targets) == 1:
                    binding = _dotted(node.targets[0])
                defs.append(MetricDef(kind=d.kind, name=d.name,
                                      name_param=d.name_param,
                                      binding=binding,
                                      lineno=node.value.lineno))
                def_ids.add(id(node.value))
    for node in _walk_own(fn):
        if isinstance(node, ast.Call) and id(node) not in def_ids:
            d = _match_metric_def(node, param_set)
            if d is not None:
                defs.append(MetricDef(kind=d.kind, name=d.name,
                                      name_param=d.name_param,
                                      binding=None, lineno=node.lineno))
                def_ids.add(id(node))
    defs.sort(key=lambda m: m.lineno)
    emits.sort(key=lambda m: m.lineno)
    return defs, emits


def _match_metric_def(node: ast.AST, params: Set[str]
                      ) -> Optional[MetricDef]:
    """``<recv>.counter(name, ...)`` / ``.gauge(name, ...)`` with a
    string-literal or parameter name -> MetricDef, else None."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("counter", "gauge")
            and node.args):
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return MetricDef(kind=node.func.attr, name=first.value,
                         name_param=None, binding=None,
                         lineno=node.lineno)
    if isinstance(first, ast.Name) and first.id in params:
        return MetricDef(kind=node.func.attr, name=None,
                         name_param=first.id, binding=None,
                         lineno=node.lineno)
    return None


# ---- CFG-lite resource analysis -----------------------------------------

_SAFE_CALL_SUFFIXES = (
    ".get", ".keys", ".values", ".items", ".append", ".copy",
    ".monotonic", ".time", ".perf_counter", ".acquire", ".release",
    ".pop", ".format", ".join", ".split", ".strip", ".encode",
    ".decode", ".setdefault", ".locked",
)
_SAFE_CALL_NAMES = {
    "len", "int", "float", "str", "bool", "max", "min", "abs",
    "isinstance", "getattr", "hasattr", "id", "repr", "list", "dict",
    "tuple", "set", "sorted", "print",
}


class _Frame:
    """One enclosing try: which keys its finally releases, whether a
    catch-all handler stops propagation."""

    def __init__(self, finally_rel: Set[str], catch_all: bool):
        self.finally_rel = finally_rel
        self.catch_all = catch_all


class _ResourceAnalyzer:
    """May-hold abstract interpretation over acquire/release events.

    State maps resource key -> acquire lineno. Branches merge with
    union (may-hold), loops run twice to catch re-acquire-before-
    release across iterations, and try frames record which keys an
    exception edge would still release (finally) or stop (catch-all
    handler)."""

    def run(self, fn) -> List[ResourceIssue]:
        self.issues: List[ResourceIssue] = []
        self._seen: Set[Tuple[str, str, int]] = set()
        self.frames: List[_Frame] = []
        end = self._block(list(fn.body), {})
        if end is not None:
            last = fn.body[-1].lineno if fn.body else fn.lineno
            for key, ln in sorted(end.items()):
                self._issue("exit", key, last, ln)
        return self.issues

    # -- events ----------------------------------------------------------

    def _issue(self, kind: str, key: str, lineno: int,
               acq: int) -> None:
        mark = (kind, key, acq)
        if mark in self._seen:
            return
        self._seen.add(mark)
        self.issues.append(ResourceIssue(kind=kind, key=key,
                                         lineno=lineno,
                                         acquire_lineno=acq))

    def _acquires(self, stmt: ast.AST) -> List[Tuple[str, int]]:
        out = []
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                key = _dotted(node.func.value)
                if key:
                    out.append((key, node.lineno))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Add):
                key = self._counter_key(node.target)
                if key:
                    out.append((key, node.lineno))
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.BinOp) \
                    and isinstance(node.value.op, ast.Add):
                key = self._counter_base(node.value.left)
                if key:
                    out.append((key, node.lineno))
        return out

    def _releases(self, stmt: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release":
                key = _dotted(node.func.value)
                if key:
                    out.add(key)
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.op, ast.Sub):
                key = self._counter_key(node.target)
                if key:
                    out.add(key)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Sub):
                key = self._counter_base(node.left)
                if key:
                    out.add(key)
        return out

    def _counter_key(self, target: ast.AST) -> Optional[str]:
        """self._inflight += 1 / self._inflight[k] += 1 -> resource key
        when the name is counter-shaped. Bare locals are excluded: a
        function-local tally cannot leak past the frame."""
        base = target.value if isinstance(target, ast.Subscript) \
            else target
        key = _dotted(base)
        if key and "." in key and _RESOURCE_NAME_RX.search(key):
            return key
        return None

    def _counter_base(self, left: ast.AST) -> Optional[str]:
        """``X.get(k, 0) + 1`` / ``X[k] + 1`` / ``X + 1`` -> X when
        counter-shaped."""
        if isinstance(left, ast.Call) \
                and isinstance(left.func, ast.Attribute) \
                and left.func.attr == "get":
            left = left.func.value
        elif isinstance(left, ast.Subscript):
            left = left.value
        key = _dotted(left)
        if key and "." in key and _RESOURCE_NAME_RX.search(key):
            return key
        return None

    def _may_raise(self, stmt: ast.AST) -> bool:
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            return True
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name is None:
                return True
            if name in _SAFE_CALL_NAMES:
                continue
            if any(name.endswith(s) for s in _SAFE_CALL_SUFFIXES):
                continue
            return True
        return False

    # -- interpretation --------------------------------------------------

    def _block(self, stmts: List[ast.stmt],
               state: Dict[str, int]) -> Optional[Dict[str, int]]:
        """Run a statement list; returns the exit state, or None when
        every path terminates (return/raise/break/continue)."""
        for stmt in stmts:
            state = self._stmt(stmt, state)
            if state is None:
                return None
        return state

    def _check_raise_edge(self, stmt: ast.AST,
                          state: Dict[str, int]) -> None:
        if not state or not self._may_raise(stmt):
            return
        covered: Set[str] = set()
        stopped = any(f.catch_all for f in self.frames)
        for f in self.frames:
            covered |= f.finally_rel
        if stopped:
            return
        for key, ln in sorted(state.items()):
            if key not in covered:
                self._issue("exception", key, stmt.lineno, ln)

    def _finally_cover(self) -> Set[str]:
        out: Set[str] = set()
        for f in self.frames:
            out |= f.finally_rel
        return out

    def _stmt(self, stmt: ast.stmt,
              state: Dict[str, int]) -> Optional[Dict[str, int]]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return state
        if isinstance(stmt, ast.Return):
            held = {k: v for k, v in state.items()
                    if k not in self._finally_cover()}
            for key, ln in sorted(held.items()):
                self._issue("exit", key, stmt.lineno, ln)
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            # held state survives into the next iteration / loop exit,
            # which is exactly how re-acquire-before-release leaks
            return state
        if isinstance(stmt, ast.Raise):
            self._check_raise_edge(stmt, state)
            return None
        if isinstance(stmt, ast.If):
            self._check_raise_edge(stmt.test, state)
            s1 = self._block(list(stmt.body), dict(state))
            s2 = self._block(list(stmt.orelse), dict(state))
            return self._merge(s1, s2)
        if isinstance(stmt, (ast.While, ast.For)):
            self._check_raise_edge(stmt, state)
            s1 = self._block(list(stmt.body), dict(state))
            base = dict(state) if s1 is None else s1
            # second pass exposes re-acquire across iterations
            s2 = self._block(list(stmt.body), dict(base))
            out = self._merge(dict(state), self._merge(s1, s2))
            if stmt.orelse and out is not None:
                out = self._block(list(stmt.orelse), out)
            return out if out is not None else dict(state)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_raise_edge(item.context_expr, state)
            return self._block(list(stmt.body), state)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, state)
        # simple statement: releases, then raise edge, then acquires
        for key in self._releases(stmt):
            state.pop(key, None)
        self._check_raise_edge(stmt, state)
        for key, ln in self._acquires(stmt):
            if key in state:
                self._issue("reacquire", key, ln, state[key])
            state[key] = ln
        return state

    def _try(self, stmt: ast.Try,
             state: Dict[str, int]) -> Optional[Dict[str, int]]:
        finally_rel: Set[str] = set()
        for s in stmt.finalbody:
            finally_rel |= self._releases(s)
        catch_all = any(
            h.type is None or (_dotted(h.type) or "").split(".")[-1]
            in ("Exception", "BaseException")
            for h in stmt.handlers)
        entry = dict(state)
        self.frames.append(_Frame(finally_rel, catch_all))
        body_state = self._block(list(stmt.body), dict(state))
        if body_state is not None and stmt.orelse:
            body_state = self._block(list(stmt.orelse), body_state)
        self.frames.pop()

        # handler paths start from "anything the body may have
        # acquired before failing"
        body_acq: Dict[str, int] = dict(entry)
        for s in stmt.body:
            for key, ln in self._acquires(s):
                body_acq.setdefault(key, ln)
        self.frames.append(_Frame(finally_rel, False))
        handler_states = []
        for h in stmt.handlers:
            hs = self._block(list(h.body), dict(body_acq))
            handler_states.append(hs)
        self.frames.pop()

        out = body_state
        for hs in handler_states:
            out = self._merge(out, hs)
        if out is None:
            return None
        for key in finally_rel:
            out.pop(key, None)
        return out

    @staticmethod
    def _merge(a: Optional[Dict[str, int]],
               b: Optional[Dict[str, int]]
               ) -> Optional[Dict[str, int]]:
        if a is None:
            return b
        if b is None:
            return a
        out = dict(a)
        for k, v in b.items():
            out[k] = min(v, out.get(k, v))
        return out
