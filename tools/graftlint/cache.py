"""Content-hash summary cache.

Persisted beside ``baseline.json`` (``tools/graftlint/cache.json``,
gitignored) so the project-wide interprocedural pass stays inside the
``--max-seconds`` CI budget as the tree grows: a file whose sha256 is
unchanged skips parsing-independent summarization entirely and loads
its :class:`~tools.graftlint.summaries.ModuleSummary` from disk.

Invalidation is per file by content hash — no mtimes, so the cache
survives checkouts/touches and never serves stale analysis after an
edit. A version bump in either the cache layout or the summary schema
drops the whole cache.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Dict, Optional

from tools.graftlint.summaries import SUMMARY_VERSION, ModuleSummary

CACHE_VERSION = 1

# where the CLI persists the cache (beside baseline.json, gitignored)
DEFAULT_CACHE = Path(__file__).parent / "cache.json"


def sha_of(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


class SummaryCache:
    def __init__(self, path: Optional[Path] = None):
        self.path = path
        self._files: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self._dirty = False
        if path is not None and path.exists():
            try:
                data = json.loads(path.read_text(encoding="utf-8"))
                if (data.get("cache_version") == CACHE_VERSION
                        and data.get("summary_version")
                        == SUMMARY_VERSION):
                    self._files = data.get("files", {})
            except (OSError, ValueError):
                self._files = {}

    def get(self, rel: str, sha: str) -> Optional[ModuleSummary]:
        ent = self._files.get(rel)
        if ent is None or ent.get("sha") != sha:
            self.misses += 1
            return None
        try:
            ms = ModuleSummary.from_dict(ent["summary"])
        except (KeyError, TypeError):
            self.misses += 1
            return None
        self.hits += 1
        return ms

    def put(self, rel: str, sha: str, summary: ModuleSummary) -> None:
        self._files[rel] = {"sha": sha, "summary": summary.to_dict()}
        self._dirty = True

    def save(self) -> None:
        if self.path is None or not self._dirty:
            return
        data = {"cache_version": CACHE_VERSION,
                "summary_version": SUMMARY_VERSION,
                "files": self._files}
        tmp = self.path.with_suffix(".json.tmp")
        try:
            tmp.write_text(json.dumps(data), encoding="utf-8")
            import os
            os.replace(tmp, self.path)
        except OSError:
            pass                    # a read-only checkout is fine
