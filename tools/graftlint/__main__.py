"""graftlint CLI.

Usage::

    python -m tools.graftlint                       # default path set
    python -m tools.graftlint deeplearning4j_tpu/ops tests/foo.py
    python -m tools.graftlint --rules host-sync,donation-safety
    python -m tools.graftlint --baseline tools/graftlint/baseline.json
    python -m tools.graftlint --write-baseline      # triage snapshot
    python -m tools.graftlint --format json
    python -m tools.graftlint --format sarif        # CI annotations
    python -m tools.graftlint --chaos-audit         # seam coverage
    python -m tools.graftlint --no-cache            # force cold scan
    python -m tools.graftlint --list-rules

Exit status: 0 clean (baselined findings don't fail), 1 when
non-baselined findings exist (or --max-seconds is exceeded), 2 on
usage errors.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from tools.graftlint.baseline import (
    DEFAULT_BASELINE, load_baseline, split_baselined, write_baseline)
from tools.graftlint.engine import REPO_ROOT, iter_files, scan
from tools.graftlint.cache import DEFAULT_CACHE
from tools.graftlint.report import (render_human, render_json,
                                    render_sarif)
from tools.graftlint.rules import ALL_RULES, get_rules
from tools.graftlint.rules.chaos_hygiene import ChaosHygieneRule
from tools.graftlint.rules.host_sync import HOT_PATHS

# the package plus the out-of-package files the host-sync rule covers
# (benchmark/worker hot loops) — everything CI lints by default
DEFAULT_PATHS = ("deeplearning4j_tpu",) + tuple(
    p for p in HOT_PATHS if not p.startswith("deeplearning4j_tpu"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX-aware static analysis "
                    "(tools/graftlint/README.md)")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/directories to scan (default: "
                         "deeplearning4j_tpu/ + the hot-path extras)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="baseline JSON: findings listed there are "
                         "reported but do not fail")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings to the baseline "
                         "file (default tools/graftlint/baseline.json, "
                         "or --baseline's path) and exit 0")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="fail if the scan takes longer than this "
                         "(the CI wall-clock budget)")
    ap.add_argument("--chaos-audit", action="store_true",
                    help="also audit fault-injection seam coverage: "
                         "flag network/file side-effects in chaos-"
                         "instrumented classes that no chaos_site "
                         "guards")
    ap.add_argument("--no-cache", action="store_true",
                    help="skip the on-disk summary cache (scan cold)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:18} {cls.description}")
        return 0

    try:
        rules = get_rules(args.rules.split(",")
                          if args.rules else None)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.chaos_audit:
        for r in rules:
            if isinstance(r, ChaosHygieneRule):
                r.audit_seams = True

    t0 = time.perf_counter()
    findings = scan(args.paths, rules,
                    cache_path=None if args.no_cache
                    else DEFAULT_CACHE)
    n_files = len(iter_files(args.paths))
    seconds = time.perf_counter() - t0

    if args.write_baseline:
        path = args.baseline if args.baseline is not None \
            else DEFAULT_BASELINE
        n = write_baseline(findings, path)
        print(f"graftlint: wrote {n} finding"
              f"{'s' if n != 1 else ''} to {path}")
        return 0

    baseline = {}
    if args.baseline is not None:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"graftlint: cannot load baseline: {e}",
                  file=sys.stderr)
            return 2
    new, baselined, stale = split_baselined(findings, baseline)

    if args.format == "json":
        render_json(new, baselined, stale, n_files, seconds)
    elif args.format == "sarif":
        render_sarif(new, baselined, stale, n_files, seconds)
    else:
        render_human(new, baselined, stale, n_files, seconds)

    if args.max_seconds is not None and seconds > args.max_seconds:
        print(f"graftlint: scan took {seconds:.2f}s, over the "
              f"--max-seconds {args.max_seconds:.0f}s budget",
              file=sys.stderr)
        return 1
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
