"""Benchmark: ResNet-50 training throughput (images/sec/chip).

The BASELINE.json headline metric (ResNet50 on TinyImageNet-shaped data,
64x64x3, 200 classes). Runs on whatever accelerator jax exposes (the driver
provides one real TPU chip; falls back to CPU with a smaller config so the
line is always produced).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
``vs_baseline`` is vs the reference's published number for this config —
the reference publishes none (SURVEY §6, BASELINE.md), so 1.0 is reported
and the absolute number is the record.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from deeplearning4j_tpu.zoo.models import ResNet50
    from deeplearning4j_tpu.optimize.updaters import Nesterovs

    if on_accel:
        batch, steps, warmup = 1024, 30, 5
        compute_dtype = "bfloat16"
    else:
        batch, steps, warmup = 16, 4, 2
        compute_dtype = "float32"

    model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype=compute_dtype,
                     updater=Nesterovs(1e-2, 0.9)).init()
    model._train_step = model._build_train_step()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)).astype(np.float32))
    idx = rng.integers(0, 200, batch)
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), idx] = 1.0
    y = jnp.asarray(y)

    import jax.random as jrandom
    key = jrandom.PRNGKey(0)

    ts = model.train_state
    # warmup (includes compile)
    for i in range(warmup):
        ts, loss = model._train_step(ts, (x,), (y,), None, None,
                                     jrandom.fold_in(key, i))
    float(loss)  # host transfer: block_until_ready alone can no-op
                 # through tunneled-device transports, inflating numbers

    t0 = time.perf_counter()
    for i in range(steps):
        ts, loss = model._train_step(ts, (x,), (y,), None, None,
                                     jrandom.fold_in(key, warmup + i))
    float(loss)
    dt = time.perf_counter() - t0
    images_per_sec = steps * batch / dt
    print(json.dumps({
        "metric": f"resnet50_64x64_{compute_dtype}_train_images_per_sec_per_chip"
                  f"_{platform}",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
    }))


def _is_transport_error(e: BaseException) -> bool:
    """True only for dropped-RPC/tunnel failures. Real regressions (shape
    errors, NaN asserts, OOM/RESOURCE_EXHAUSTED) must NOT be retried."""
    if isinstance(e, (ConnectionError, TimeoutError)):
        return True
    try:
        import jax
        if isinstance(e, jax.errors.JaxRuntimeError):
            msg = str(e).upper()
            return any(t in msg for t in
                       ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CONNECTION",
                        "SOCKET", "TRANSPORT", "RPC"))
    except ImportError:
        pass
    return False


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        if not _is_transport_error(e):
            raise
        # tunneled-device transports occasionally drop a compile/execute
        # RPC; one retry protects the recorded metric
        import traceback
        traceback.print_exc()
        time.sleep(5)
        main()
