"""Benchmark: ResNet-50 training throughput (images/sec/chip).

The BASELINE.json headline metric (ResNet50 on TinyImageNet-shaped data,
64x64x3, 200 classes). Runs on whatever accelerator jax exposes (the driver
provides one real TPU chip; falls back to CPU with a smaller config so the
line is always produced).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
``vs_baseline`` is vs the reference's published number for this config —
the reference publishes none (SURVEY §6, BASELINE.md), so 1.0 is reported
and the absolute number is the record.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    from deeplearning4j_tpu.zoo.models import ResNet50
    from deeplearning4j_tpu.optimize.updaters import Nesterovs

    if on_accel:
        # Round 4: fused blocks WIN — FusedBottleneckBlock(impl="xla")
        # with Gram-matrix BN statistics for the expanding projections
        # (Σy = colsum(e)@W, Σy² = diag(WᵀGW); ops/fused_conv.py
        # conv_bn_stats_xla) removes the 4f-activation stat reads. The
        # batch sweet spot moved with the new balance: 384 → 45.2k,
        # 256 → 43.5k, 512 → 41.4k (unfused: 256 → 40.6k, 384 → 38.1k).
        # K steps/dispatch shrinks the ~26-30 ms tunnel overhead to
        # ~0.1 ms/step.
        # Round 5 adds the space-to-depth stem (s2d_stem): the 7×7/2
        # 3-channel conv1 — which underfills the 128-lane MXU — becomes
        # the exactly-equivalent 4×4/1 conv on 12 channels (weights
        # refold losslessly, fold_stem_weights). Measured: 45.1k → 46.7k.
        batch, k, dispatches, warmup = 384, 170, 2, 1
        compute_dtype = "bfloat16"
        fused = dict(fused_blocks=True, fused_impl="xla", s2d_stem=True)
    else:
        batch, k, dispatches, warmup = 16, 2, 2, 1
        compute_dtype = "float32"
        fused = {}

    model = ResNet50(num_classes=200, height=64, width=64, channels=3,
                     compute_dtype=compute_dtype,
                     updater=Nesterovs(1e-2, 0.9), **fused).init()

    # K optimizer steps per dispatch (lax.scan in optimize/solver.py:
    # make_scan_train_step): per-dispatch fixed overhead (buffer-handle
    # marshalling; ~26 ms through the tunneled transport, measured in
    # benchmarks/step_overhead.py) otherwise caps throughput regardless
    # of device speed. Batches are staged device-side once (broadcast
    # view) so dispatches don't re-transfer data — the shapes, not the
    # contents, determine the timing.
    from deeplearning4j_tpu.optimize.solver import make_scan_train_step

    def loss_fn(params, mstate, feats, labels, fmask, lmask, rng, it):
        return model._loss(params, mstate, (feats,), (labels,), fmask,
                           lmask, rng, it)

    steps_fn = make_scan_train_step(loss_fn, model._tx)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 64, 64, 3)).astype(np.float32))
    idx = rng.integers(0, 200, batch)
    y = np.zeros((batch, 200), np.float32)
    y[np.arange(batch), idx] = 1.0
    y = jnp.asarray(y)
    xs = jnp.broadcast_to(x, (k,) + x.shape)
    ys = jnp.broadcast_to(y, (k,) + y.shape)

    import jax.random as jrandom
    key = jrandom.PRNGKey(0)

    ts = model.train_state
    for i in range(warmup):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, i))
    float(losses[-1])  # host transfer: block_until_ready alone can no-op
                       # through tunneled-device transports

    t0 = time.perf_counter()
    for i in range(dispatches):
        ts, losses = steps_fn(ts, xs, ys, None, None,
                              jrandom.fold_in(key, warmup + i))
    float(losses[-1])
    dt = time.perf_counter() - t0
    images_per_sec = dispatches * k * batch / dt
    # vs_baseline: round-1's recorded number for this exact config
    # (BASELINE.md: 29,119 img/s/chip; the reference publishes none)
    base = 29119.0 if on_accel else None
    print(json.dumps({
        "metric": f"resnet50_64x64_{compute_dtype}_train_images_per_sec_per_chip"
                  f"_{platform}",
        "value": round(images_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(images_per_sec / base, 3) if base else 1.0,
    }))


def _is_transport_error(e: BaseException) -> bool:
    """True only for dropped-RPC/tunnel failures. Real regressions (shape
    errors, NaN asserts, OOM/RESOURCE_EXHAUSTED) must NOT be retried."""
    if isinstance(e, (ConnectionError, TimeoutError)):
        return True
    try:
        import jax
        if isinstance(e, jax.errors.JaxRuntimeError):
            msg = str(e).upper()
            return any(t in msg for t in
                       ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CONNECTION",
                        "SOCKET", "TRANSPORT", "RPC"))
    except ImportError:
        pass
    return False


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        if not _is_transport_error(e):
            raise
        # tunneled-device transports occasionally drop a compile/execute
        # RPC; one retry protects the recorded metric
        import traceback
        traceback.print_exc()
        time.sleep(5)
        main()
